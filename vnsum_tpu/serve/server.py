"""Online serving HTTP front-end (stdlib, like the demo server — runs on
TPU hosts with no extra packages).

    python -m vnsum_tpu.serve.server --backend fake --port 8901
    python -m vnsum_tpu.serve.server --backend tpu --model llama3.2:3b \
        --max-batch 16 --max-wait-ms 10

Endpoints:
    POST /v1/summarize  {"text": ..., "approach": "mapreduce",
                         "deadline_ms"?, "max_new_tokens"?, "request_id"?}
        Full strategy run. The strategy's rounds are submitted through the
        micro-batching scheduler, so concurrent summarize requests share
        engine batches.
    POST /v1/generate   {"prompt": str} | {"prompts": [str, ...]},
                        optional "max_new_tokens", "temperature", "top_k",
                        "top_p", "seed", "deadline_ms", "request_id",
                        "reference"/"references", "cache_hint"/"cache_hints",
                        "stream"
        Raw engine call(s) through the queue. ``"stream": true`` (single
        prompt) answers as Server-Sent Events: ``delta`` events carry text
        as decode segments retire it (concatenated deltas are byte-
        identical to the final text) and the terminal ``done`` event
        carries the exact non-streaming payload. /v1/summarize accepts
        ``stream`` too (``progress`` events per strategy round + the same
        ``done`` payload).

    Multi-tenant QoS (--tenants, serve/qos.py): requests carry an X-Tenant
    header; tenants share the engine by weighted-fair (deficit-round-robin)
    scheduling, token-rate quotas shed typed 429 QUOTA with a refill-derived
    Retry-After, and batch-tier requests are preemptible in --inflight mode
    (typed PREEMPTED/REQUEUED journal lifecycle, byte-identical completion).
    GET /healthz        liveness + queue depth
    GET /v1/requests/<id>  durable-serving poll surface (--journal-dir):
                        status + result of a journaled request — the
                        reconnect path after a server crash mid-request
    DELETE /v1/requests/<id>  first-class cancellation: idempotent,
                        gang-cancels <id>#N fan-out children; queued
                        requests resolve immediately, slot residents are
                        evicted (without requeue) at the next segment
                        boundary, and a typed CANCELLED terminal event
                        rides the journal so replay never resurrects a
                        cancelled request. Streaming requests also cancel
                        automatically on client disconnect once the
                        bounded resume window (--stream-idle-timeout-s)
                        expires; within it, a reconnect with Last-Event-ID
                        resumes via one full-text snapshot event
    GET /metrics        Prometheus text (serve/metrics.py): counters plus
                        queue-wait/TTFT/e2e/occupancy/spec histograms;
                        with --slo also the vnsum_serve_slo_* burn-rate
                        gauges, per-tenant usage series, and OpenMetrics-
                        style trace_id exemplars on the latency buckets
    GET /v1/usage       per-tenant usage ledger (serve/usage.py): token/
                        outcome counters + windowed latency quantiles;
                        ?tenant= filters one tenant
    GET /debug/slo      SLO engine detail (--slo, serve/slo.py): per-
                        objective compliance, fast/slow burn rates, error
                        budget remaining, breach state, exemplar trace ids
    GET /debug/flightrecorder
                        the flight recorder's typed-event ring
                        (obs/recorder.py); anomalies also dump it to
                        --flight-dir
    GET /debug/stacks   every thread's Python stack on demand — the manual
                        twin of the watchdog's automatic stall dump
                        (serve/watchdog.py); SIGUSR1 writes the same
                        snapshot to --flight-dir. /healthz carries the
                        watchdog verdict (last-beat age per registered
                        thread, stall/recovery counters)
    GET /debug/trace    Chrome trace-event JSON of the recent-request ring
                        (vnsum_tpu.obs) — load in ui.perfetto.dev; one track
                        per request, one per engine batch. ?save=1 also
                        writes the dump into --trace-dir.

Request correlation: every response carries an ``X-Request-Id`` header and a
``request_id`` JSON field — client-supplied (JSON "request_id" or an
X-Request-Id request header) or generated — and the same id names the
request's track in /debug/trace and its ServeRequestRecord.trace_id.

Sheds (queue full, token budget, deadline, shutdown) return HTTP 429 with a
typed JSON body {"error": "shed", "reason": "<queue_full|...>"} — the
admission-control contract, machine-readable for client backoff.

Each HTTP handler thread blocks on its request futures; ThreadingHTTPServer
gives us one thread per in-flight request, and the scheduler coalesces
across them. Strategy objects are constructed once per approach and reused
across requests/threads — they are re-entrant by contract (all per-run
state is local to summarize_batch; see strategies/base.py).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..backend.base import Backend, get_backend
from ..core.config import APPROACHES, GenerationConfig, PipelineConfig, approach_defaults
from ..core.logging import get_logger
from ..obs import ObsHub
from ..obs.export import save_timestamped_trace
from ..strategies import get_strategy
from ..text import clean_thinking_tokens
from .queue import RequestCancelled, RequestShed, ShedReason
from .scheduler import MicroBatchScheduler
from .supervisor import RequestFailed

logger = get_logger("vnsum.serve.http")


class ServeState:
    """Everything the handler needs: the scheduler (which owns the engine)
    plus a lazily-built per-approach strategy cache."""

    def __init__(
        self,
        backend: Backend,
        *,
        max_batch: int = 8,
        max_wait_s: float = 0.01,
        max_queue_depth: int = 256,
        max_queued_tokens: int = 0,
        default_deadline_s: float | None = None,
        default_spec_k: int = 0,
        trace_sample: float = 1.0,
        trace_ring: int = 256,
        trace_dir: str | None = None,
        inflight: bool = False,
        slots: int | None = None,
        slot_prompt_tokens: int = 0,
        fused_segments: int = 1,
        supervisor=None,
        supervise: bool = True,
        journal_dir: str | None = None,
        journal_fsync_s: float = 0.05,
        mesh=None,
        tenants=None,
        stream_heartbeat_s: float = 15.0,
        stream_idle_timeout_s: float = 10.0,
        slo: str | None = None,
        slo_fast_s: float = 60.0,
        slo_slow_s: float = 600.0,
        slo_burn_fast: float = 10.0,
        slo_burn_slow: float = 1.0,
        flight_dir: str | None = None,
        flight_events: int = 4096,
        flight_recorder: bool = True,
        windowed_metrics: bool = True,
        watchdog: bool = True,
        watchdog_interval_s: float = 0.5,
        watchdog_stall_s: float = 10.0,
        watchdog_dispatch_base_s: float = 30.0,
        watchdog_dispatch_per_token_s: float = 0.01,
        watchdog_exit_on_escalate: bool = True,
    ) -> None:
        self.backend = backend
        # uptime anchors for /healthz (monotonic for the math, wall clock
        # for the human-readable start stamp)
        self.started_monotonic = time.monotonic()
        self.started_wall = time.time()
        # stream hardening (serve/stream.py): SSE keepalive cadence (0 =
        # no heartbeats) and the bounded resume window — a streaming
        # request whose consumer disconnected and never reattached within
        # the idle window is CANCELLED by the scheduler sweep; 0 cancels
        # immediately on disconnect (no resume window at all)
        self.stream_heartbeat_s = max(float(stream_heartbeat_s), 0.0)
        self.stream_idle_timeout_s = max(float(stream_idle_timeout_s), 0.0)
        # live streams by request id — the Last-Event-ID reconnect surface
        from .stream import StreamRegistry

        self.streams = StreamRegistry()
        # multi-tenant QoS (serve/qos.py): a TenantTable arms per-tenant
        # weighted-fair scheduling + token-rate quotas in the queue and
        # the X-Tenant header on the HTTP surface; batch-tier tenants'
        # requests become preemptible in in-flight mode. None = every
        # caller is one class, the pre-QoS contract
        self.tenants = tenants
        # multi-chip serving descriptor: a jax Mesh (or any mapping-shaped
        # stand-in with the same {axis: size} semantics, for hermetic
        # benches) — surfaced on /healthz and as vnsum_serve_mesh_* gauges;
        # the backend itself was already built against it
        self.mesh = mesh
        # durability (serve/journal.py): a --journal-dir arms the
        # write-ahead request journal — ACCEPT/START/COMPLETE/FAILED per
        # request, replayed by replay_journal() after a restart. None =
        # volatile serving, the pre-journal contract
        self.journal = None
        if journal_dir:
            from .journal import RequestJournal

            self.journal = RequestJournal(
                journal_dir, fsync_interval_s=journal_fsync_s
            )
        # /readyz gate: a journal-armed server is not routable until
        # startup replay has re-enqueued (or deadline-expired) every
        # unfinished ACCEPT — the fleet router must not send fresh traffic
        # ahead of crash recovery. Journal-less servers are ready at birth
        self._replay_done = self.journal is None
        # fault tolerance (serve/supervisor.py): ON by default for the HTTP
        # front-end — engine failures are classified, survivors retried,
        # poison requests bisected out, and repeated resource failures step
        # the degradation ladder down to a typed 503 brownout. supervise=
        # False (--no-supervise) restores the raw fail-the-batch contract
        if supervisor is None and supervise:
            from .supervisor import EngineSupervisor

            supervisor = EngineSupervisor()
        self.supervisor = supervisor
        # mirrors the backend's GenerationConfig(spec_k=...) default so a
        # request-built config (which REPLACES the backend default) keeps it
        self.default_spec_k = default_spec_k
        # tracing (vnsum_tpu.obs): trace_sample=0 disables it outright — no
        # hub, no RequestTrace allocations, `is None` checks only (the
        # serving-bench <2% overhead criterion runs in that mode). The
        # always-on histograms in serve/metrics.py are independent of this.
        self.obs = (
            ObsHub(sample=trace_sample, ring=trace_ring)
            if trace_sample > 0 else None
        )
        self.trace_dir = trace_dir
        if trace_dir:
            # arm the existing device-profile hook (core/profiling.py): any
            # device_profile() call in this process now lands its XLA trace
            # next to the Chrome dumps written here
            os.environ.setdefault("VNSUM_PROFILE_DIR", trace_dir)
        # production observability (this PR's tentpole): rolling-window
        # metrics + per-tenant usage ledger (serve/metrics.py over
        # obs/window.py), the flight recorder (obs/recorder.py), and the
        # SLO engine (serve/slo.py). windowed_metrics=False /
        # flight_recorder=False are the bench A/B's all-off levers — never
        # operator flags (always-on is the serving contract)
        from .metrics import ServeMetrics

        self.metrics = ServeMetrics(
            windowed=windowed_metrics,
            horizon_s=max(slo_slow_s, 2 * slo_fast_s),
            sub_windows=60,
        )
        self.metrics.usage_window_s = slo_fast_s
        if tenants is not None:
            # declared tenants get their labels ahead of any traffic: a
            # hostile name burst can never evict a table tenant's series
            self.metrics.seed_tenants(tenants.stats().keys())
        from ..obs.recorder import FlightRecorder

        self.recorder = (
            FlightRecorder(capacity=flight_events, directory=flight_dir)
            if flight_recorder else None
        )
        # liveness (serve/watchdog.py, this PR's tentpole): heartbeat
        # registry + bounded-dispatch contract + stall recovery. ON by
        # default — hang detection is part of the serving contract;
        # watchdog=False is the bench A/B's off arm, never an operator
        # flag (--no-watchdog exists for debugging a misbehaving detector,
        # not for production). Escalation (lock/helper stalls, where a
        # replacement thread would deadlock too) is a supervised
        # journal-seal-and-exit: WATCHDOG_EXIT_CODE tells the process
        # manager to restart, and journal replay restores state.
        # watchdog_exit_on_escalate=False (tests/benches embedding a
        # ServeState in-process) records + seals but keeps the process
        self.watchdog = None
        self._watchdog_escalations = 0
        if watchdog:
            from .watchdog import Watchdog

            self._watchdog_exit = watchdog_exit_on_escalate
            self.watchdog = Watchdog(
                interval_s=watchdog_interval_s,
                loop_deadline_s=watchdog_stall_s,
                helper_deadline_s=max(watchdog_stall_s * 6, 60.0),
                dispatch_base_s=watchdog_dispatch_base_s,
                dispatch_per_token_s=watchdog_dispatch_per_token_s,
                recorder=self.recorder,
                dump_dir=flight_dir,
                on_escalate=self._watchdog_escalate,
            )
        common = dict(
            max_batch=max_batch,
            max_wait_s=max_wait_s,
            max_queue_depth=max_queue_depth,
            max_queued_tokens=max_queued_tokens,
            metrics=self.metrics,
            obs=self.obs,
            trace_dir=trace_dir,
            supervisor=supervisor,
            journal=self.journal,
            tenants=tenants,
            recorder=self.recorder,
            watchdog=self.watchdog,
        )
        if inflight:
            # in-flight batching (serve/inflight.py): slot-feeding over the
            # backend's persistent decode loop — joiners enter at segment
            # boundaries instead of waiting out strangers' batches
            from .inflight import InflightScheduler

            self.scheduler = InflightScheduler(
                backend, slots=slots,
                slot_prompt_tokens=slot_prompt_tokens,
                fused_segments=fused_segments, **common,
            )
        else:
            self.scheduler = MicroBatchScheduler(backend, **common)
        if self.stream_idle_timeout_s > 0:
            # arm the scheduler's idle-consumer sweep: abandoned streams
            # (disconnect, no resume) cancel after this window
            self.scheduler.stream_idle_timeout_s = self.stream_idle_timeout_s
        # SLO engine (--slo): declarative objectives judged over the
        # rolling windows; sustained fast burn fires the flight recorder.
        # Surfaced (healthz/metrics/debug), never coupled into the ladder
        self.slo = None
        if slo:
            from .slo import SloEngine, parse_slo_spec

            self.slo = SloEngine(
                parse_slo_spec(slo) if isinstance(slo, str) else slo,
                self.metrics,
                fast_window_s=slo_fast_s,
                slow_window_s=slo_slow_s,
                breach_fast_burn=slo_burn_fast,
                breach_slow_burn=slo_burn_slow,
                recorder=self.recorder,
                # helper-kind heartbeat: a wedged SLO evaluation is a
                # detected stall, not a silent end of judgement
                heartbeat=(
                    self.watchdog.register("slo-monitor", kind="helper")
                    if self.watchdog is not None else None
                ),
            )
        if self.watchdog is not None:
            # monitor thread starts LAST: every heartbeat is registered
            # (and freshly beaten) before the first detection pass
            self.watchdog.start()
        self.default_deadline_s = default_deadline_s
        self._strategies: dict[str, object] = {}
        import threading

        self._strategies_lock = threading.Lock()

    def strategy_for(self, approach: str, max_new_tokens: int | None = None):
        """ONE strategy instance per approach, shared across requests and
        threads (the re-entrancy contract in strategies/base.py). It is
        constructed against the RAW backend — splitters capture its
        count_tokens, which must stay a direct host-side call — and each
        request passes its own deadline-bound QueuedBackend via the
        summarize(..., backend=) override, so generation rides the queue
        while token counting does not. A per-request max_new_tokens
        override bypasses the cache (the budget is baked in at
        construction)."""
        if max_new_tokens is not None:
            cfg = PipelineConfig(
                approach=approach,
                **{**approach_defaults(approach),
                   "max_new_tokens": int(max_new_tokens)},
            )
            return get_strategy(approach, self.backend, cfg)
        with self._strategies_lock:
            strat = self._strategies.get(approach)
            if strat is None:
                cfg = PipelineConfig(
                    approach=approach, **approach_defaults(approach)
                )
                strat = get_strategy(approach, self.backend, cfg)
                self._strategies[approach] = strat
            return strat

    def mesh_state(self) -> dict | None:
        """{devices, data, model} for /healthz and the mesh gauges (None =
        single-chip serving, nothing rendered). Accepts a jax Mesh or any
        {axis: size} mapping so hermetic benches can exercise the surface."""
        if self.mesh is None:
            return None
        shape = dict(getattr(self.mesh, "shape", None) or self.mesh)
        devices = 1
        for size in shape.values():
            devices *= int(size)
        return {
            "devices": devices,
            "data": int(shape.get("data", 1)),
            "model": int(shape.get("model", 1)),
        }

    def replay_journal(self) -> int:
        """Re-enqueue every journaled ACCEPT that never reached a terminal
        outcome, through the normal supervised path. Greedy replays are
        byte-identical to an uninterrupted run (the ACCEPT record carries
        the full payload incl. the sampling seed; the engine is
        deterministic per payload). Entries whose wall-clock deadline
        already passed fail typed (``shed:deadline``) without burning
        engine time. Idempotent: the journal hands each unfinished entry
        out at most once per process, so calling this twice enqueues
        once."""
        if self.journal is None:
            return 0
        t0 = time.monotonic()
        n = 0
        # rebuild live gang groups FIRST: replayed members must rejoin
        # their structured job (membership and partiality come from the
        # journal's typed GANG records, not from re-deriving trace prefixes)
        restored = self.scheduler.gangs.restore(
            self.journal.gangs_unfinished()
        )
        if restored:
            logger.info("journal replay: restored %d live gang(s)", restored)
        for entry in self.journal.take_unfinished():
            p = entry.payload
            deadline_unix = p.get("deadline_unix")
            if deadline_unix is not None and time.time() >= deadline_unix:
                self.journal.fail(
                    entry.rid, "shed:deadline", "expired before replay"
                )
                continue
            deadline = (
                time.monotonic() + (deadline_unix - time.time())
                if deadline_unix is not None else None
            )
            cfg = None
            if p.get("config") is not None:
                c = dict(p["config"])
                c["eos_ids"] = tuple(c.get("eos_ids") or ())
                cfg = GenerationConfig(**c)
            try:
                # internal=True: admission was already granted (and
                # journaled) in the previous life of this server — replay
                # must not shed against the depth budget of an empty queue
                self.scheduler.submit(
                    p.get("prompt", ""),
                    max_new_tokens=p.get("max_new_tokens"),
                    config=cfg,
                    deadline=deadline,
                    internal=True,
                    reference=p.get("reference"),
                    cache_hint=p.get("cache_hint"),
                    trace_id=p.get("trace_id") or entry.rid,
                    trace_owned=True,
                    journal_rid=entry.rid,
                    # the QoS class rides the ACCEPT payload: a replayed
                    # batch-tier request stays preemptible and keeps
                    # billing its tenant
                    tenant=p.get("tenant", ""),
                    tier=p.get("tier", "interactive"),
                    gang=p.get("gang", ""),
                    gang_phase=p.get("gang_phase", ""),
                )
            # lint-allow[swallowed-exception]: a shutdown shed at replay is already journaled typed-FAILED by the queue's on_shed hook — the ledger entry is resolved
            except RequestShed:
                continue
            n += 1
        self.journal.note_replay(n, time.monotonic() - t0)
        if self.recorder is not None:
            self.recorder.record("journal_replay", replayed=n,
                                 seconds=round(time.monotonic() - t0, 6))
        if n:
            logger.info("journal replay: re-enqueued %d request(s)", n)
        self._replay_done = True
        return n

    def readiness(self) -> tuple[bool, str]:
        """The ``/readyz`` verdict: (routable, reason). Distinct from
        ``/healthz`` liveness — a draining, browned-out, or pre-replay
        server is alive (healthz answers) but must not receive fresh
        traffic, and the router's probe loop keys off exactly this split.
        Reasons are typed: ``draining`` (shutdown drain underway, never
        coming back), ``pre_replay`` (journal recovery still re-enqueuing
        — route after replay), ``brownout`` (supervisor ladder bottomed
        out — route again once the rung recovers)."""
        if self.scheduler.closed:
            return False, "draining"
        if not self._replay_done:
            return False, "pre_replay"
        if self.supervisor is not None:
            from .supervisor import Rung

            if self.supervisor.rung >= Rung.BROWNOUT:
                return False, "brownout"
        return True, "ready"

    def obs_snapshot(self) -> dict:
        """``GET /debug/obs/snapshot`` — the federation scrape payload:
        everything the fleet router folds into its rollups in ONE JSON
        round trip (no Prometheus text parsing on the hot scrape path).
        ``mono_now`` is this process's monotonic clock at snapshot time —
        the router pairs it with its own send/receive stamps to estimate
        the per-worker clock offset (RTT midpoint) that aligns worker
        spans into the merged fleet trace."""
        from ..obs.export import trace_state_payload

        ready, reason = self.readiness()
        payload: dict = {
            "mono_now": time.monotonic(),
            "ready": ready,
            "readyz_reason": reason,
            "queue_depth": self.scheduler.queue.depth,
            **self.metrics.federation_snapshot(),
        }
        if self.supervisor is not None:
            payload["degraded_rung"] = int(self.supervisor.rung)
        if self.slo is not None:
            slo = self.slo.evaluate()
            objectives = slo.get("objectives", {})
            payload["slo"] = {
                "breached": bool(slo.get("breached")),
                "burn_fast_max": max(
                    (o["burn_fast"] for o in objectives.values()),
                    default=0.0,
                ),
                "objectives": {
                    name: {k: o[k] for k in ("kind", "compliance",
                                             "burn_fast", "burn_slow",
                                             "budget_remaining",
                                             "breaching")}
                    for name, o in objectives.items()
                },
            }
        usage = self.metrics.usage_snapshot(self.metrics.usage_window_s)
        if usage is not None:
            payload["usage"] = usage
            payload["usage_window_s"] = self.metrics.usage_window_s
        if self.watchdog is not None:
            ages = self.watchdog.stats_dict().get("heartbeat_ages", {})
            payload["watchdog"] = {
                "max_heartbeat_age_s": max(ages.values(), default=0.0),
                "heartbeat_ages": ages,
            }
        if self.obs is not None:
            payload["traces"] = trace_state_payload(self.obs.snapshot()[0])
        return payload

    def incident_dump(self, incident: str) -> dict:
        """``POST /debug/dump?incident=<id>`` — this worker's contribution
        to a router-minted incident bundle: the flight-recorder ring, a
        stack snapshot, and the clock stamp that lets the report CLI order
        this process's events against the others'. The ring additionally
        dumps to the worker's own --flight-dir (throttled, tagged with the
        incident id) so the evidence survives even if the router dies
        mid-collection."""
        from .watchdog import snapshot_stacks

        payload: dict = {
            "incident": incident,
            "mono_now": time.monotonic(),
            "wall_now": time.time(),
            "stacks": snapshot_stacks(),
        }
        if self.recorder is not None:
            payload["flightrecorder"] = self.recorder.snapshot()
            dump_path = self.recorder.dump(f"incident_{incident}")
            if dump_path is not None:
                payload["dump_path"] = str(dump_path)
        if self.watchdog is not None:
            payload["watchdog"] = self.watchdog.health_dict()
        return payload

    def cancel_request(self, rid: str) -> dict | None:
        """``DELETE /v1/requests/<id>`` — gang-cancel ``rid`` and its
        ``rid#N`` fan-out children everywhere in the lifecycle. Returns the
        response payload, or None for a wholly unknown id (typed 404
        upstream). Idempotent: re-DELETEs answer with zero counts and the
        ledger's terminal status. With the journal on, a non-terminal
        ledger entry forces the scheduler mark even when no live request is
        visible (handoff windows), and entries the scheduler can no longer
        see (queued in a previous process life, not yet replayed — replay
        runs before traffic, so only a race can leave one) are closed
        directly so restart replay can never resurrect them."""
        entries = self.journal.lookup(rid) if self.journal is not None else []
        nonterminal = [e for e in entries if not e.terminal]
        res = self.scheduler.cancel(rid, force_mark=bool(nonterminal))
        if not res["known"] and not entries:
            return None
        if self.journal is not None and nonterminal and not res["cancel_pending"]:
            # belt and braces for ledger entries with no live request: the
            # scheduler mark covers every handoff, this closes the record
            # (idempotent — the journal no-ops on terminal entries, and a
            # live request resolving later no-ops against this)
            for e in nonterminal:
                self.journal.cancel(e.rid, "api")
        payload: dict = {
            "request_id": rid,
            "cancelled_queued": res["cancelled_queued"],
            "cancel_pending": res["cancel_pending"],
        }
        if self.journal is not None:
            from .journal import aggregate_status

            entries = self.journal.lookup(rid)
            if entries:
                payload["status"] = aggregate_status(entries)
        if "status" not in payload:
            payload["status"] = (
                "cancelling" if res["cancel_pending"] else "cancelled"
            )
        return payload

    def _watchdog_escalate(self, stall) -> None:
        """Lock/helper-stall escalation (serve/watchdog.py): the big
        hammer. A thread wedged in a LOCK wait (e.g. mid-fsync inside the
        journal lock) cannot be replaced — the successor would deadlock on
        the same lock — so the supervised answer is seal-and-exit: dump the
        flight ring, best-effort seal the journal on a side thread (the
        wedged thread may HOLD the journal lock, so the seal gets a bounded
        wait, and an unsealed journal replays fine — that is the normal
        crash path), and exit with WATCHDOG_EXIT_CODE so the process
        manager restarts us and journal replay restores every accepted
        request. Runs on the watchdog thread."""
        import threading as _threading

        from .watchdog import WATCHDOG_EXIT_CODE

        self._watchdog_escalations += 1
        logger.critical(
            "watchdog escalation: %s stall on %r (%.2fs past %.2fs) — "
            "sealing the journal and exiting %d for a supervised restart",
            stall.kind, stall.name, stall.stalled_for_s, stall.limit_s,
            WATCHDOG_EXIT_CODE,
        )
        if self.recorder is not None:
            self.recorder.dump("watchdog_escalate")
        if self.journal is not None:
            t = _threading.Thread(target=self.journal.seal, daemon=True)
            t.start()
            t.join(timeout=2.0)
        if not self._watchdog_exit:
            return  # embedded/test mode: the verdict is recorded, we live
        os._exit(WATCHDOG_EXIT_CODE)

    def close(self, drain_timeout_s: float = 30.0) -> None:
        if self.watchdog is not None:
            # the monitor stops FIRST: a drain parked in journal seal or a
            # slow final dispatch must never be declared a stall mid-exit
            self.watchdog.close()
        if self.slo is not None:
            self.slo.close()
        self.scheduler.close(drain=True, timeout=drain_timeout_s)
        if self.journal is not None:
            # drain first so every completion is journaled, then mark the
            # shutdown clean; drain-overrun sheds are typed FAILED records,
            # so the seal is honest either way
            self.journal.seal()
            self.journal.close()
        if self.recorder is not None:
            # SIGTERM-drain dump: the recorder's last act — the full drain
            # (including any overrun sheds) is in the ring it writes out
            self.recorder.dump("drain")


class _BadRequest(ValueError):
    """Client-side input error → HTTP 400, never the 500/engine-error path."""


def _number(req: dict, key: str, cast, *, integer: bool = False):
    val = req.get(key)
    if val is None:
        return None
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        raise _BadRequest(f"{key!r} must be a number")
    if integer and not float(val).is_integer():
        raise _BadRequest(f"{key!r} must be an integer")
    return cast(val)


def _deadline_from(req: dict, default_s: float | None) -> float | None:
    ms = _number(req, "deadline_ms", float)
    if ms is not None:
        return time.monotonic() + ms / 1000.0
    if default_s is not None:
        return time.monotonic() + default_s
    return None


def _request_id(req: dict, headers) -> str:
    """The request's end-to-end correlation id: client-supplied (JSON
    "request_id", else an X-Request-Id header) or generated. The same id is
    echoed in the response header/body, names the trace track in
    /debug/trace, and lands in every ServeRequestRecord.trace_id the request
    produces."""
    rid = req.get("request_id")
    if rid is None:
        rid = headers.get("X-Request-Id")
    if rid is None:
        return uuid.uuid4().hex[:16]
    if not isinstance(rid, str) or not rid.strip() or len(rid) > 128:
        raise _BadRequest(
            "'request_id' must be a non-empty string of at most 128 chars"
        )
    return rid.strip()


def _gen_config_from(
    req: dict, default_spec_k: int = 0
) -> GenerationConfig | None:
    knobs = {}
    for key, cast, integer in (
        ("temperature", float, False),
        ("top_k", int, True),
        ("top_p", float, False),
        ("seed", int, True),
        # per-request speculative-decoding override; the server-level
        # default comes from --spec-k
        ("spec_k", int, True),
    ):
        val = _number(req, key, cast, integer=integer)
        if val is not None:
            knobs[key] = val
    if not knobs:
        return None  # backend's own GenerationConfig default applies
    # a request that customizes only sampling knobs must not silently turn
    # the server's --spec-k default off: a fresh GenerationConfig would
    # carry spec_k=0 and fully REPLACE the backend default
    knobs.setdefault("spec_k", default_spec_k)
    return GenerationConfig(**knobs)


def make_handler(state: ServeState):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: every response carries Content-Length, so persistent
        # connections work — load generators and real clients reuse sockets
        # instead of paying a TCP handshake per request
        protocol_version = "HTTP/1.1"

        # set per-request by the POST handlers once the id is known; _json
        # then echoes it as X-Request-Id and a request_id body field on every
        # outcome (200, 429 shed, 500) so clients can always correlate
        _rid: str | None = None

        def _json(self, payload: dict, status: int = 200,
                  headers: dict | None = None) -> None:
            if self._rid is not None:
                payload = {"request_id": self._rid, **payload}
            body = json.dumps(payload, ensure_ascii=False).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json; charset=utf-8")
            if self._rid is not None:
                self.send_header("X-Request-Id", self._rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _shed_response(self, e: RequestShed) -> None:
            """The typed shed contract: admission/deadline/quota sheds are
            429, a supervisor BROWNOUT is 503 — and EVERY shed carries a
            Retry-After header, derived where the shed was decided (queue
            depth for queue_full/token_budget, the tenant bucket's exact
            refill for quota, 1s for an expired client deadline) — the
            machine-readable back-off signal."""
            payload: dict = {"error": "shed", "reason": e.reason.value}
            status = 503 if e.reason is ShedReason.BROWNOUT else 429
            retry_after = e.retry_after_s or 1.0
            payload["retry_after_s"] = retry_after
            # Retry-After is delta-seconds, integral, at least 1
            headers = {"Retry-After": str(max(1, int(round(retry_after))))}
            self._json(payload, status, headers)

        def _text(self, body: str, status: int = 200,
                  content_type: str = "text/plain; version=0.0.4; "
                                      "charset=utf-8") -> None:
            raw = body.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            self._rid = None  # keep-alive: one handler serves many requests
            path, _, query = self.path.partition("?")
            if path == "/debug/trace":
                if state.obs is None:
                    self._json(
                        {"error": "tracing disabled (--trace-sample 0)"}, 404
                    )
                    return
                trace = state.obs.chrome_trace()
                import urllib.parse

                save = urllib.parse.parse_qs(query).get("save", ["0"])[0]
                if state.trace_dir and save == "1":
                    p = save_timestamped_trace(trace, state.trace_dir, "serve")
                    logger.info("wrote trace dump %s", p)
                body = json.dumps(trace).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/debug/obs/snapshot":
                # the federation scrape surface: counters + raw histogram
                # state + slo/usage/readyz/watchdog views + raw request
                # spans, one JSON document (serve/federation.py)
                self._json(state.obs_snapshot())
            elif path == "/debug/slo":
                if state.slo is None:
                    self._json({"error": "no SLOs configured (--slo unset)"},
                               404)
                    return
                self._json(state.slo.debug_payload())
            elif path == "/debug/flightrecorder":
                if state.recorder is None:
                    self._json({"error": "flight recorder disabled"}, 404)
                    return
                self._json(state.recorder.snapshot())
            elif path == "/debug/stacks":
                # every thread's Python stack on demand — the manual twin
                # of the watchdog's automatic stall dump (SIGUSR1 writes
                # the same snapshot to disk). Always available: hangs are
                # exactly when an operator needs this, watchdog or not
                from .watchdog import snapshot_stacks

                payload = {"threads": snapshot_stacks()}
                if state.watchdog is not None:
                    payload["watchdog"] = state.watchdog.health_dict()
                self._json(payload)
            elif path == "/v1/usage":
                self._usage(query)
            elif path.startswith("/v1/requests/"):
                self._request_status(path[len("/v1/requests/"):])
            elif path == "/readyz":
                # routability, not liveness: typed 503 while draining,
                # browned-out, or pre-replay so a router/LB can tell
                # "alive but do not route" from dead (which never answers)
                ready, reason = state.readiness()
                if ready:
                    self._json({"status": "ready"})
                else:
                    self._json(
                        {"error": "not_ready", "reason": reason,
                         "retry_after_s": 1.0},
                        503, {"Retry-After": "1"},
                    )
            elif path == "/healthz":
                sup = state.supervisor
                from .. import __version__

                payload = {
                    "status": "ok",
                    "backend": state.backend.name,
                    "version": __version__,
                    "started_at": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ",
                        time.gmtime(state.started_wall),
                    ),
                    "uptime_s": round(
                        time.monotonic() - state.started_monotonic, 3
                    ),
                    # this process's monotonic clock at render time: the
                    # fleet router reads it against its own probe send/
                    # receive stamps (RTT midpoint) to estimate the clock
                    # offset the merged /debug/trace corrects by
                    "mono_now": time.monotonic(),
                    "queue_depth": state.scheduler.queue.depth,
                    "queued_tokens": state.scheduler.queue.queued_tokens,
                    "closed": state.scheduler.closed,
                }
                if state.slo is not None:
                    # the one-line SLO verdict: probes and humans read the
                    # same judgement the gauges and /debug/slo render
                    payload["slo"] = state.slo.status_line()
                if state.watchdog is not None:
                    # liveness verdict: last-beat age per registered thread
                    # plus the stall/recovery counters — a probe reading
                    # /healthz sees a wedged loop as a growing age, then a
                    # counted stall, without waiting for client timeouts
                    payload["watchdog"] = state.watchdog.health_dict()
                mesh_state = state.mesh_state()
                if mesh_state is not None:
                    # echo the serving mesh so probes/load balancers can
                    # verify the topology a replica actually runs with
                    payload["mesh"] = mesh_state
                if state.tenants is not None:
                    # echo the QoS table (name -> weight/rate/tier) so
                    # operators can verify what a replica actually enforces
                    payload["tenants"] = {
                        name: {k: t[k]
                               for k in ("weight", "token_rate", "tier")}
                        for name, t in state.tenants.stats().items()
                    }
                if sup is not None:
                    # the degradation ladder is health surface: "ok" only
                    # at HEALTHY, "degraded" on any lower rung so probes
                    # and load balancers see the brownout coming
                    rung = sup.rung
                    payload["degraded_rung"] = int(rung)
                    payload["degraded"] = rung.name.lower()
                    if rung > 0:
                        payload["status"] = "degraded"
                self._json(payload)
            elif path == "/metrics":
                cache_stats = getattr(
                    state.backend, "prefix_cache_stats", lambda: None
                )()
                slot_state = getattr(
                    state.scheduler, "slot_state", lambda: None
                )()
                mesh_state = state.mesh_state()
                if mesh_state is not None and slot_state is not None:
                    # per-DP-replica occupancy: busy slots spread over the
                    # data axis (each replica holds slots/data rows)
                    mesh_state["replica_occupancy"] = (
                        slot_state[1] / mesh_state["data"]
                    )
                # exemplars only for scrapers that NEGOTIATE OpenMetrics:
                # the classic text-format parser (the default Prometheus
                # Accept) rejects the trailing `# {...}` after a sample
                # and would drop the entire scrape
                openmetrics = (
                    "application/openmetrics-text"
                    in (self.headers.get("Accept") or "")
                )
                body = state.scheduler.metrics.render_prometheus(
                        queue_depth=state.scheduler.queue.depth,
                        queued_tokens=state.scheduler.queue.queued_tokens,
                        cache_stats=cache_stats,
                        slot_state=slot_state,
                        mesh_state=mesh_state,
                        degraded_rung=(
                            int(state.supervisor.rung)
                            if state.supervisor is not None else None
                        ),
                        journal_stats=(
                            state.journal.stats_dict()
                            if state.journal is not None else None
                        ),
                        qos_state=(
                            state.tenants.stats()
                            if state.tenants is not None else None
                        ),
                        gang_state=state.scheduler.gangs.stats(),
                        slo_state=(
                            state.slo.export_state()
                            if state.slo is not None else None
                        ),
                        recorder_stats=(
                            state.recorder.stats_dict()
                            if state.recorder is not None else None
                        ),
                        watchdog_stats=(
                            state.watchdog.stats_dict()
                            if state.watchdog is not None else None
                        ),
                        exemplars=openmetrics,
                    )
                if openmetrics:
                    # the OpenMetrics exposition requires the EOF marker
                    self._text(
                        body + "# EOF\n",
                        content_type="application/openmetrics-text; "
                                     "version=1.0.0; charset=utf-8",
                    )
                else:
                    self._text(body)
            else:
                self._json({"error": "not found"}, 404)

        def _usage(self, query: str) -> None:
            """``GET /v1/usage[?tenant=]`` — the per-tenant usage ledger:
            monotonic token/outcome counters plus windowed latency
            quantiles per tenant (serve/usage.py). 404s when the metrics
            were built without rolling windows, or for a tenant the ledger
            has never seen."""
            import urllib.parse

            from .usage import TenantLabelRegistry

            usage = state.metrics.usage_snapshot(
                state.metrics.usage_window_s
            )
            if usage is None:
                self._json(
                    {"error": "usage accounting disabled "
                              "(windowed metrics off)"}, 404,
                )
                return
            q = urllib.parse.parse_qs(query)
            tenant = q.get("tenant", [None])[0]
            payload = {
                "window_s": state.metrics.usage_window_s,
                "tenants": usage,
            }
            if tenant is not None:
                # ledger rows are keyed by SANITIZED names ('team a' was
                # accounted as 'team_a') — map the query the same way, but
                # never through canonical(): a read must not grow the
                # registry or charge its overflow counter
                tenant = TenantLabelRegistry.sanitize(tenant)
                if tenant not in usage:
                    self._json(
                        {"error": f"no usage recorded for tenant "
                                  f"{tenant!r}"}, 404,
                    )
                    return
                payload["tenants"] = {tenant: usage[tenant]}
            self._json(payload)

        def _request_status(self, raw_rid: str) -> None:
            """``GET /v1/requests/<id>`` — the reconnect-and-poll surface
            of durable serving: a client whose connection died in a crash
            polls the id it submitted (journaled request ids are echoed on
            every response) and reads the replayed outcome, including the
            COMPLETE result text."""
            import urllib.parse

            rid = urllib.parse.unquote(raw_rid)
            if state.journal is None:
                self._json(
                    {"error": "journaling disabled (--journal-dir unset)"},
                    404,
                )
                return
            entries = state.journal.lookup(rid)
            if not entries:
                # typed 404, never a 500 — unknown/expired ids are a
                # client-visible state, not a server fault
                self._json(
                    {"error": f"unknown or expired request id {rid!r}"}, 404
                )
                return
            # retry/fan-out aggregation (incl. the cancelled and partial
            # states) is the ONE shared fold in serve/journal.py — the
            # DELETE surface uses the same one, so the two can never
            # disagree
            from .journal import EV_COMPLETE, EV_STREAM, aggregate_status

            payload = {
                "request_id": rid,
                "status": aggregate_status(entries),
                "entries": [e.to_dict() for e in entries],
            }
            # structured jobs: the typed GANG records turn the flat entry
            # list into PER-PHASE progress (map 12/40 done, reduce started)
            # — a polling client of a long fan-out sees where it is, not
            # just a state fold
            ginfo = (state.journal.gang_info(rid)
                     or state.scheduler.gangs.lookup(rid))
            if ginfo and ginfo.get("members"):
                by_rid = {e.rid: e for e in entries}
                phases: dict[str, dict] = {}
                for mrid, phase in ginfo["members"].items():
                    ph = phases.setdefault(
                        phase or "unphased",
                        {"total": 0, "done": 0, "failed": 0, "running": 0,
                         "streaming": 0},
                    )
                    ph["total"] += 1
                    e = by_rid.get(mrid)
                    if e is None:
                        ph["running"] += 1
                    elif e.status == EV_COMPLETE:
                        ph["done"] += 1
                    elif e.terminal:
                        ph["failed"] += 1
                    else:
                        ph["running"] += 1
                        if e.status == EV_STREAM:
                            ph["streaming"] += 1
                payload["gang"] = {
                    "members": len(ginfo["members"]),
                    "partial": bool(ginfo.get("partial")),
                    "phases": phases,
                }
            self._json(payload)

        # request bodies beyond this are refused outright: a huge (or
        # negative, which would read to EOF and wedge the handler thread)
        # Content-Length must not buffer unbounded bytes per connection
        MAX_BODY_BYTES = 16 * 1024 * 1024

        def _read_json(self) -> dict | None:
            try:
                length = int(self.headers.get("Content-Length", "0"))
            # lint-allow[swallowed-exception]: a garbled header becomes length=-1, which the branch below answers with a typed 400
            except ValueError:
                length = -1
            if length < 0 or length > self.MAX_BODY_BYTES:
                # refusing WITHOUT reading the body leaves its bytes in the
                # stream — the next keep-alive request would parse as
                # garbage, so drop the connection after responding
                self.close_connection = True
                if length < 0:
                    self._json({"error": "bad Content-Length"}, 400)
                else:
                    self._json({"error": "request body too large"}, 413)
                return None
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                self._json({"error": "invalid JSON"}, 400)
                return None
            except UnicodeDecodeError:
                # json.loads raises this (not JSONDecodeError) for bodies
                # that aren't valid UTF-8 — without the catch it would
                # surface as a 500 engine-error path for a client bug
                self._json({"error": "request body is not valid UTF-8"}, 400)
                return None
            if not isinstance(req, dict):
                self._json({"error": "malformed request"}, 400)
                return None
            return req

        def _reject_unknown_fields(self, req: dict, allowed: frozenset) -> bool:
            """Typed 400 for unknown top-level fields: a typo'd knob
            (``temperatre``) silently ignored is a misconfigured request
            served with wrong parameters — refuse loudly instead. Returns
            True when the request was rejected."""
            unknown = [k for k in req if k not in allowed]
            if unknown:
                self._json({
                    "error": f"unknown field(s): {', '.join(sorted(unknown))}",
                    "allowed": sorted(allowed),
                }, 400)
                return True
            return False

        GENERATE_FIELDS = frozenset({
            "prompt", "prompts", "max_new_tokens", "temperature", "top_k",
            "top_p", "seed", "spec_k", "deadline_ms", "request_id",
            "reference", "references", "cache_hint", "cache_hints",
            "stream",
        })
        SUMMARIZE_FIELDS = frozenset({
            "text", "approach", "max_new_tokens", "deadline_ms", "request_id",
            "stream",
        })

        def _qos_class(self) -> tuple[str, str] | None:
            """(tenant, tier) from the X-Tenant header against the QoS
            table; no table -> the single-class default. An unknown tenant
            is a typed 400 (never a silent default bucket) — returns None
            after responding."""
            if state.tenants is None:
                return "", "interactive"
            from .qos import UnknownTenant

            try:
                spec = state.tenants.resolve(self.headers.get("X-Tenant"))
            except UnknownTenant as e:
                self._json({"error": str(e)}, 400)
                return None
            return spec.name, spec.tier

        def _stream_requested(self, req: dict) -> bool:
            return bool(req.get("stream"))

        # -- SSE plumbing (serve/stream.py) ---------------------------------

        def _sse_begin(self) -> None:
            """Open the event stream: no Content-Length (the response ends
            when the request does), so the connection closes after — the
            one response shape keep-alive can't carry."""
            self.close_connection = True
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream; charset=utf-8")
            self.send_header("Cache-Control", "no-store")
            if self._rid is not None:
                self.send_header("X-Request-Id", self._rid)
            self.send_header("Connection", "close")
            self.end_headers()

        def _sse_event(self, name: str, payload: dict,
                       seq: int | None = None) -> None:
            data = json.dumps(payload, ensure_ascii=False)
            frame = f"event: {name}\ndata: {data}\n\n"
            if seq is not None:
                # SSE event id: the channel's monotone seq — what a
                # reconnecting client sends back as Last-Event-ID
                frame = f"id: {seq}\n" + frame
            self.wfile.write(frame.encode())
            self.wfile.flush()
            state.scheduler.metrics.observe_stream_events()

        def _stream_response(self, channel, done, finish,
                             gen: int | None = None) -> str:
            """Open the SSE response and drain ``channel`` until ``done()``
            turns true and the channel is empty, then write the terminal
            event from ``finish()`` -> (event_name, payload). The terminal
            payload of a successful request is THE SAME payload the
            non-streaming path returns. Returns the drain outcome
            ("finished" / "disconnected" / "detached" — see
            _drain_stream); the CALLER decides what cancellation a
            disconnect implies; the engine side always owns its own
            lifecycle."""
            try:
                self._sse_begin()
            # lint-allow[swallowed-exception]: returning the outcome IS the answer — a client gone before the headers flushed takes the same disconnect policy as one gone mid-stream
            except OSError:
                logger.info("streaming client disconnected before headers "
                            "(%s)", self._rid)
                return "disconnected"
            return self._drain_stream(channel, done, finish, gen)

        def _drain_stream(self, channel, done, finish,
                          gen: int | None = None) -> str:
            """The one SSE drain loop (first connection and Last-Event-ID
            resume both end here; headers are already on the wire).
            Returns "finished" (terminal event reached the socket),
            "disconnected" (client gone — the caller runs the disconnect
            policy), or "detached" (a Last-Event-ID reconnect superseded
            this consumer — the NEW handler owns the stream, so the caller
            must neither cancel nor unregister). Quiet stretches emit
            ``: heartbeat`` comment frames every ``--stream-heartbeat-s``:
            idle proxies keep the connection, and the write doubles as the
            disconnect probe for requests that are between segments (a
            dead socket fails the write -> OSError -> the caller's
            disconnect policy)."""
            from .stream import StreamDetached

            metrics = state.scheduler.metrics
            metrics.observe_stream_open(+1)
            hb = state.stream_heartbeat_s
            try:
                last_write = time.monotonic()
                while True:
                    try:
                        ev = channel.pop(0.05, gen)
                    # lint-allow[swallowed-exception]: detachment IS the resolution — a reconnecting consumer owns the stream now; this stale handler must exit without writing a terminal frame
                    except StreamDetached:
                        return "detached"
                    if ev is not None:
                        self._sse_event(ev[0], ev[1], ev[2])
                        last_write = time.monotonic()
                        continue
                    if done() and channel.empty():
                        break
                    if hb and time.monotonic() - last_write >= hb:
                        self.wfile.write(b": heartbeat\n\n")
                        self.wfile.flush()
                        metrics.observe_stream_heartbeat()
                        last_write = time.monotonic()
                self._sse_event(*finish())
                return "finished"
            # lint-allow[swallowed-exception]: returning the outcome IS the answer — the caller runs the disconnect policy (cancel now or leave the bounded resume window open); the engine side resolves and journals regardless
            except OSError:
                logger.info("streaming client disconnected (%s)", self._rid)
                return "disconnected"
            finally:
                metrics.observe_stream_open(-1)

        @staticmethod
        def _stream_error_event(e: Exception) -> tuple[str, dict]:
            """The ONE exception -> terminal SSE error event mapping, shared
            by the generate and summarize stream paths (mirrors the typed
            non-streaming contract: shed reason + Retry-After hint,
            supervised failure class, raw error)."""
            if isinstance(e, RequestShed):
                return "error", {
                    "error": "shed", "reason": e.reason.value,
                    "retry_after_s": e.retry_after_s or 1.0,
                }
            if isinstance(e, RequestCancelled):
                # the typed terminal for a withdrawn request — what a
                # Last-Event-ID reconnect after the resume window reads
                return "error", {"error": "cancelled", "stage": e.stage,
                                 "reason": e.reason}
            if isinstance(e, RequestFailed):
                return "error", {"error": "request_failed",
                                 "class": e.failure_class.value,
                                 "detail": str(e)}
            return "error", {"error": str(e)}

        def _stream_finish_generate(self, fut):
            """Terminal SSE event for a streamed /v1/generate: the exact
            non-streaming payload on success, a typed error event
            otherwise."""
            try:
                # lint-allow[unbounded-blocking-wait]: externally bounded — the drain loop only calls finish() after fut.done() turned true, so this result() never blocks
                c = fut.result()
            except Exception as e:
                return self._stream_error_event(e)
            return "done", {
                "request_id": self._rid,
                "completions": [{"text": c.text,
                                 "record": c.record.to_dict()}],
            }

        def do_POST(self) -> None:  # noqa: N802 (stdlib API)
            self._rid = None  # keep-alive: one handler serves many requests
            path, _, query = self.path.partition("?")
            if path == "/v1/generate":
                self._generate()
            elif path == "/v1/summarize":
                self._summarize()
            elif path == "/debug/dump":
                # correlated incident capture: the router fans this out to
                # every worker with a minted incident id; the response IS
                # this worker's bundle contribution (ring + stacks + clock)
                import urllib.parse

                raw = urllib.parse.parse_qs(query).get(
                    "incident", ["manual"]
                )[0]
                incident = re.sub(r"[^A-Za-z0-9_.-]", "_", raw)[:64] or \
                    "manual"
                # drain the (typically empty) body so keep-alive survives
                length = int(self.headers.get("Content-Length") or 0)
                if length > 0:
                    self.rfile.read(min(length, self.MAX_BODY_BYTES))
                self._json(state.incident_dump(incident))
            else:
                self._json({"error": "not found"}, 404)

        def do_DELETE(self) -> None:  # noqa: N802 (stdlib API)
            """``DELETE /v1/requests/<id>`` — first-class cancellation:
            idempotent, gang-cancels ``<id>#N`` fan-out children, answers
            with the request's aggregated status plus how many queued
            requests resolved immediately and how many engine-side ones
            will be reclaimed at the next segment boundary. Unknown ids are
            a typed 404."""
            self._rid = None
            path = self.path.partition("?")[0]
            if not path.startswith("/v1/requests/"):
                self._json({"error": "not found"}, 404)
                return
            import urllib.parse

            rid = urllib.parse.unquote(path[len("/v1/requests/"):])
            self._rid = rid
            payload = state.cancel_request(rid)
            if payload is None:
                self._json(
                    {"error": f"unknown request id {rid!r}"}, 404
                )
                return
            self._json(payload)

        def _generate(self) -> None:
            req = self._read_json()
            if req is None:
                return
            if self._reject_unknown_fields(req, self.GENERATE_FIELDS):
                return
            prompts = req.get("prompts")
            if prompts is None:
                prompt = req.get("prompt")
                prompts = [prompt] if isinstance(prompt, str) else None
            if not prompts or not all(isinstance(p, str) and p for p in prompts):
                self._json({"error": "need 'prompt' or non-empty 'prompts'"}, 400)
                return
            # speculation references: "reference" (single) or "references"
            # (aligned with prompts; null entries allowed)
            references = req.get("references")
            if references is None:
                ref = req.get("reference")
                references = [ref] * len(prompts) if isinstance(ref, str) else None
            if references is not None and (
                not isinstance(references, list)
                or len(references) != len(prompts)
                or not all(r is None or isinstance(r, str) for r in references)
            ):
                self._json(
                    {"error": "'references' must align with prompts"}, 400
                )
                return
            # prefix-cache hints: "cache_hint" (single, applied to every
            # prompt) or "cache_hints" (aligned; null entries allowed)
            cache_hints = req.get("cache_hints")
            if cache_hints is None:
                hint = req.get("cache_hint")
                cache_hints = (
                    [hint] * len(prompts) if isinstance(hint, str) else None
                )
            if cache_hints is not None and (
                not isinstance(cache_hints, list)
                or len(cache_hints) != len(prompts)
                or not all(h is None or isinstance(h, str) for h in cache_hints)
            ):
                self._json(
                    {"error": "'cache_hints' must align with prompts"}, 400
                )
                return
            try:
                self._rid = _request_id(req, self.headers)
                max_new_tokens = _number(req, "max_new_tokens", int, integer=True)
                config = _gen_config_from(req, state.default_spec_k)
                deadline = _deadline_from(req, state.default_deadline_s)
            except _BadRequest as e:
                self._json({"error": str(e)}, 400)
                return
            qos = self._qos_class()
            if qos is None:
                return
            tenant, tier = qos
            if self._stream_requested(req):
                if len(prompts) != 1:
                    self._json(
                        {"error": "'stream' needs exactly one prompt"}, 400
                    )
                    return
                self._generate_stream(
                    prompts[0], max_new_tokens, config, deadline,
                    references[0] if references else None,
                    cache_hints[0] if cache_hints else None,
                    tenant, tier,
                )
                return
            # one RequestTrace for the whole HTTP request: multi-prompt
            # calls put each prompt's spans on its own sub-track
            trace = (
                state.obs.start_request(
                    self._rid, parent=self.headers.get("X-Parent-Span"))
                if state.obs is not None else None
            )
            try:
                completions = state.scheduler.generate_sync(
                    prompts,
                    max_new_tokens=max_new_tokens,
                    config=config,
                    deadline=deadline,
                    references=references,
                    cache_hints=cache_hints,
                    trace=trace,
                    trace_id=self._rid,
                    # this handler made the sampling decision (trace may be
                    # None = sampled out) — the scheduler must not re-draw
                    trace_owned=True,
                    tenant=tenant,
                    tier=tier,
                )
            except RequestShed as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, f"shed:{e.reason.value}")
                self._shed_response(e)
                return
            except RequestCancelled as e:
                # someone DELETEd this id (or its stream was abandoned)
                # while this waiter blocked: typed 409, never a 500
                if state.obs is not None:
                    state.obs.finish_request(trace, f"cancelled:{e.reason}")
                self._json({"error": "cancelled", "stage": e.stage,
                            "reason": e.reason}, 409)
                return
            except RequestFailed as e:
                # supervision gave up: typed terminal failure (poison
                # quarantine, exhausted retries, fatal engine error)
                if state.obs is not None:
                    state.obs.finish_request(trace, "error")
                logger.exception("generate failed after supervision")
                self._json({"error": "request_failed",
                            "class": e.failure_class.value,
                            "detail": str(e)}, 500)
                return
            except Exception as e:  # engine failure: surface, don't crash
                if state.obs is not None:
                    state.obs.finish_request(trace, "error")
                logger.exception("generate failed")
                self._json({"error": str(e)}, 500)
                return
            if state.obs is not None:
                state.obs.finish_request(trace, "ok")
            self._json(
                {
                    "completions": [
                        {"text": c.text, "record": c.record.to_dict()}
                        for c in completions
                    ]
                }
            )

        def _generate_stream(self, prompt, max_new_tokens, config, deadline,
                             reference, cache_hint, tenant, tier) -> None:
            """Streamed /v1/generate: the request rides the scheduler like
            any other, plus a StreamChannel the in-flight harvest pushes
            decode-progress deltas into at every segment boundary (the
            one-shot path emits one final delta). Concatenated deltas are
            byte-identical to the done event's text — the stream.py delta
            discipline. Admission sheds happen BEFORE the stream opens and
            answer as plain typed 429s.

            Disconnect policy: the stream is registered for Last-Event-ID
            resume, so a dropped connection leaves the request running for
            the BOUNDED idle window (--stream-idle-timeout-s) — reattach in
            time and the stream continues from a snapshot; don't, and the
            scheduler's sweep cancels it (automatic cancel-on-disconnect).
            A zero window cancels right here, before this handler returns."""
            from .stream import StreamChannel

            if self.headers.get("Last-Event-ID") is not None:
                # reconnect: attach to the live stream instead of
                # submitting a duplicate request
                self._resume_stream()
                return
            trace = (
                state.obs.start_request(
                    self._rid, parent=self.headers.get("X-Parent-Span"))
                if state.obs is not None else None
            )
            channel = StreamChannel(
                self._rid, metrics=state.scheduler.metrics
            )
            try:
                fut = state.scheduler.submit(
                    prompt,
                    max_new_tokens=max_new_tokens,
                    config=config,
                    deadline=deadline,
                    reference=reference,
                    cache_hint=cache_hint,
                    trace=trace,
                    trace_id=self._rid,
                    # this handler made the sampling decision (trace may be
                    # None = sampled out) — the scheduler must not re-draw
                    trace_owned=True,
                    tenant=tenant,
                    tier=tier,
                    stream=channel,
                )
            except RequestShed as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, f"shed:{e.reason.value}")
                self._shed_response(e)
                return
            if state.obs is not None and trace is not None:
                # finalize the trace when the REQUEST resolves, not when
                # this handler exits: a disconnected stream keeps decoding
                # through the resume window, and its spans must still land
                # in /debug/trace whether it completes, errors, or is
                # cancelled by the sweep (the callback fires exactly once,
                # on whichever thread resolves the future)
                def _finalize_trace(f, _trace=trace):
                    e = f.exception()
                    if isinstance(e, RequestCancelled):
                        status = f"cancelled:{e.reason}"
                    else:
                        status = "ok" if e is None else "error"
                    state.obs.finish_request(_trace, status)

                fut.add_done_callback(_finalize_trace)
            state.streams.register(self._rid, channel, fut)
            gen = channel.attach()
            outcome = self._stream_response(
                channel, fut.done,
                lambda: self._stream_finish_generate(fut), gen=gen,
            )
            if outcome == "finished":
                state.streams.unregister(self._rid)
            elif outcome == "disconnected" and state.stream_idle_timeout_s == 0:
                # no resume window configured: a disconnect IS the cancel
                state.scheduler.cancel(self._rid, reason="disconnect")
                state.streams.unregister(self._rid)
            # else: disconnected within the idle window (stay registered —
            # the request keeps decoding; a reconnect resumes it, the sweep
            # cancels it) or detached (the resumed handler owns the stream
            # now — cancelling here would kill the live reconnect)

        def _resume_stream(self) -> None:
            """``Last-Event-ID`` reconnect: reattach to the registered
            channel (superseding any stale handler), replay ONE full-text
            ``snapshot`` event off the producer's high-water mark —
            buffered deltas are folded in, so snapshot + subsequent deltas
            still reassemble the exact final text — then continue live.
            Unknown/expired ids answer a typed 404; a request that already
            finished (or was cancelled past the idle window) replays its
            snapshot and goes straight to the terminal event."""
            entry = state.streams.get(self._rid)
            if entry is None:
                self._json(
                    {"error": "no resumable stream for request id "
                              f"{self._rid!r}"}, 404,
                )
                return
            channel, fut = entry
            gen = channel.attach()
            state.scheduler.metrics.observe_stream_resume()
            text, seq = channel.resume_snapshot()
            try:
                self._sse_begin()
                self._sse_event("snapshot", {"text": text}, seq)
            # lint-allow[swallowed-exception]: the resuming client vanished before its snapshot landed — the stream stays registered and the idle window keeps running; nothing to resolve here
            except OSError:
                logger.info("resume client disconnected (%s)", self._rid)
                return
            outcome = self._drain_stream(
                channel, fut.done,
                lambda: self._stream_finish_generate(fut), gen,
            )
            if outcome == "finished":
                state.streams.unregister(self._rid)

        def _summarize(self) -> None:
            req = self._read_json()
            if req is None:
                return
            if self._reject_unknown_fields(req, self.SUMMARIZE_FIELDS):
                return
            text = req.get("text", "")
            if not isinstance(text, str) or not text.strip():
                self._json({"error": "empty document"}, 400)
                return
            approach = req.get("approach", "mapreduce")
            if approach not in APPROACHES:
                self._json(
                    {"error": f"unknown approach {approach!r}",
                     "approaches": list(APPROACHES)}, 400,
                )
                return
            try:
                self._rid = _request_id(req, self.headers)
                max_new_tokens = _number(req, "max_new_tokens", int, integer=True)
                deadline = _deadline_from(req, state.default_deadline_s)
            except _BadRequest as e:
                self._json({"error": str(e)}, 400)
                return
            qos = self._qos_class()
            if qos is None:
                return
            tenant, tier = qos
            # the trace survives every strategy round: all the request's
            # fanned-out prompts record onto it through the QueuedBackend
            trace = (
                state.obs.start_request(
                    self._rid, parent=self.headers.get("X-Parent-Span"))
                if state.obs is not None else None
            )
            qbackend = state.scheduler.backend_view(
                deadline=deadline, trace=trace, trace_id=self._rid,
                tenant=tenant, tier=tier, gang=self._rid,
            )
            t0 = time.monotonic()

            def payload_from(result) -> dict:
                recs = qbackend.records
                payload = {
                    "approach": approach,
                    "summary": clean_thinking_tokens(result.summary),
                    "num_chunks": result.num_chunks,
                    "llm_calls": result.llm_calls,
                    "serving": {
                        "llm_requests": len(recs),
                        "queue_wait_s": round(sum(r.queue_wait_s for r in recs), 6),
                        "engine_s": round(sum(r.engine_s for r in recs), 6),
                        "generated_tokens": sum(r.generated_tokens for r in recs),
                        "draft_tokens": sum(r.draft_tokens for r in recs),
                        "accepted_tokens": sum(r.accepted_tokens for r in recs),
                        "total_s": round(time.monotonic() - t0, 6),
                    },
                }
                # degraded fan-out (a POISON member was dropped from the
                # reduce): say so on the reply, not just in the journal
                ginfo = (
                    state.scheduler.gangs.lookup(self._rid)
                    or (state.journal.gang_info(self._rid)
                        if state.journal is not None else None)
                )
                if ginfo and ginfo.get("partial"):
                    payload["partial"] = True
                return payload

            try:
                # request-level admission: the strategy's rounds fan out as
                # INTERNAL submits that bypass the depth budget (a wide map
                # round must not shed itself on an idle server), so the
                # queue/token gate applies here, once, per request — and it
                # bills the whole document against the tenant's quota. The
                # full-document tokenization is only worth paying when a
                # token budget or a tenant table is actually configured
                est_tokens = (
                    state.backend.count_tokens(text)
                    if state.scheduler.queue.max_queued_tokens
                    or state.tenants is not None
                    else 0
                )
                # gang admission: ONE pass through the gate admits the
                # whole fan-out (billed once) and opens the structured-job
                # group every internal submit below joins
                gang = state.scheduler.admit_gang(
                    self._rid, est_tokens, tenant=tenant
                )
            except RequestShed as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, f"shed:{e.reason.value}")
                self._shed_response(e)
                return
            if self._stream_requested(req):
                try:
                    self._summarize_stream(
                        text, approach, max_new_tokens, qbackend, trace,
                        payload_from,
                    )
                finally:
                    gang.finish()
                return
            try:
                strategy = state.strategy_for(approach, max_new_tokens)
                result = strategy.summarize(text, backend=qbackend)
            except RequestShed as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, f"shed:{e.reason.value}")
                self._shed_response(e)
                return
            except RequestCancelled as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, f"cancelled:{e.reason}")
                self._json({"error": "cancelled", "stage": e.stage,
                            "reason": e.reason}, 409)
                return
            except RequestFailed as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, "error")
                logger.exception("summarize failed after supervision")
                self._json({"error": "request_failed",
                            "class": e.failure_class.value,
                            "detail": str(e)}, 500)
                return
            except Exception as e:
                if state.obs is not None:
                    state.obs.finish_request(trace, "error")
                logger.exception("summarize failed")
                self._json({"error": str(e)}, 500)
                return
            else:
                # build the reply while the live group still exists — the
                # partial flag must survive even with journaling off
                reply = payload_from(result)
            finally:
                # the structured job terminally resolved either way: flush
                # any unflushed membership and drop the live group (the
                # journal keeps the durable record)
                gang.finish()
            if state.obs is not None:
                state.obs.finish_request(trace, "ok")
            self._json(reply)

        def _summarize_stream(self, text, approach, max_new_tokens,
                              qbackend, trace, payload_from) -> None:
            """Streamed /v1/summarize: the strategy runs on a worker thread
            while this handler streams SSE. Deltas here are PROGRESS events
            (one per completed strategy round — a summarize's token stream
            would interleave its map fan-out); the done event carries the
            exact non-streaming reply payload."""
            import threading

            from .stream import StreamChannel

            channel = StreamChannel(self._rid, metrics=state.scheduler.metrics)
            metrics = state.scheduler.metrics
            metrics.observe_stream_request()

            def progress(done_prompts: int) -> None:
                channel.push_event("progress", {
                    "llm_requests_done": done_prompts,
                })

            qbackend.progress = progress
            box: dict = {}

            def run() -> None:
                try:
                    strategy = state.strategy_for(approach, max_new_tokens)
                    box["result"] = strategy.summarize(text, backend=qbackend)
                # lint-allow[swallowed-exception]: the error is delivered, not swallowed — finish() reads the box and renders it as the stream's typed terminal error event
                except Exception as e:
                    box["error"] = e

            worker = threading.Thread(
                target=run, name="vnsum-serve-stream-summarize", daemon=True
            )
            worker.start()

            def finish():
                worker.join()
                e = box.get("error")
                if e is None:
                    return "done", {"request_id": self._rid,
                                    **payload_from(box["result"])}
                logger.error("streamed summarize failed: %s", e)
                return self._stream_error_event(e)

            outcome = self._stream_response(
                channel, lambda: not worker.is_alive(), finish
            )
            if outcome != "finished":
                # (no gen is passed for summarize streams, so the only
                # non-finished outcome here is a real disconnect)
                # client gone mid-summarize: reclaim instead of logging and
                # decoding to completion — gang-cancel the fan-out (every
                # child shares this trace_id, so queued siblings resolve
                # now and engine residents at the next boundary), stop the
                # progress pushes, and drop the channel's buffer. The
                # worker unblocks with RequestCancelled out of its next
                # round and the strategy run ends
                state.scheduler.cancel(self._rid, reason="disconnect")
                qbackend.progress = None
                channel.close()
            # a client disconnect skips finish() (nobody to write to), but
            # the strategy run still owns the trace: wait it out before
            # finalizing, so spans never land on a finished trace and the
            # recorded status reflects the run's real outcome
            worker.join()
            if state.obs is not None:
                status = "ok"
                e = box.get("error")
                if isinstance(e, RequestCancelled):
                    status = "cancelled:disconnect"
                elif e is not None:
                    status = "error"
                state.obs.finish_request(trace, status)

        def log_message(self, fmt, *args):  # route through our logger
            logger.info("%s %s", self.address_string(), fmt % args)

    return Handler


class _Server(ThreadingHTTPServer):
    # socketserver's default listen backlog of 5 collapses under a connect
    # burst (SYN retransmit backoff shows up as multi-second tail latency
    # on clients that were never even admitted); a serving front-end wants
    # the kernel queueing connects, not clients retransmitting
    request_queue_size = 128
    daemon_threads = True


def make_server(
    state: ServeState, host: str = "127.0.0.1", port: int = 8901
) -> ThreadingHTTPServer:
    return _Server((host, port), make_handler(state))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="vnsum-serve")
    p.add_argument("--backend", choices=["tpu", "ollama", "hf", "fake"],
                   default="fake")
    p.add_argument("--model", default="llama3.2:3b")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8901)
    p.add_argument("--max-batch", type=int, default=8,
                   help="engine batch ceiling per dispatch")
    p.add_argument("--max-new-tokens", type=int, default=1024,
                   help="tpu backend: default decode budget (must be < the "
                        "model's max_seq_len — small configs like --model "
                        "tiny need this lowered)")
    p.add_argument("--max-wait-ms", type=float, default=10.0,
                   help="max time a head-of-line request waits for company")
    p.add_argument("--mesh", default=None,
                   help='multi-chip serving mesh spec, e.g. "data=2,model=4"'
                        " (tpu backend only): shards the engine's decode/"
                        "prefill/slot-loop programs over the named axes — "
                        "batch rows over data, heads over model. Validated "
                        "against jax.device_count(); echoed on /healthz and "
                        "as vnsum_serve_mesh_* gauges")
    p.add_argument("--inflight", action="store_true",
                   help="in-flight batching: admit new requests into the "
                        "running decode batch at segment boundaries "
                        "(tpu/fake backends; greedy outputs identical)")
    p.add_argument("--slots", type=int, default=None,
                   help="in-flight decode slots (default: --max-batch)")
    p.add_argument("--slot-prompt-tokens", type=int, default=0,
                   help="in-flight prompt bucket S; longer prompts fall "
                        "back to one-shot dispatch (0 = full context)")
    p.add_argument("--fused-segments", type=int, default=1,
                   help="fused multi-step decode: on-device segments per "
                        "slot-loop dispatch (the host polls asynchronously "
                        "and joins/cancels/streams at the fused cadence; "
                        "N>1 amortizes the dispatch/sync tax at small "
                        "batch, trading TTFT/poll latency bounded by N — "
                        "greedy outputs identical at every N)")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission control: max queued requests")
    p.add_argument("--max-queued-tokens", type=int, default=0,
                   help="admission control: max queued prompt tokens (0=off)")
    p.add_argument("--default-deadline-ms", type=float, default=None,
                   help="deadline applied to requests that carry none")
    p.add_argument("--spec-k", type=int, default=0,
                   help="reference-guided speculative decoding: draft up to "
                        "K tokens/step from each request's reference text "
                        "(0 = off; greedy outputs are identical either way)")
    p.add_argument("--cache-blocks", type=int, default=256,
                   help="radix prefix KV cache: HBM block budget for "
                        "cross-request prompt-prefix reuse (tpu/fake "
                        "backends; greedy outputs are identical either way)")
    p.add_argument("--cache-block-tokens", type=int, default=64,
                   help="tokens per prefix-cache block (reuse granularity)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable the prefix KV cache outright")
    p.add_argument("--no-supervise", action="store_true",
                   help="disable engine supervision (retry/bisect/"
                        "degradation ladder); failures fail the whole batch "
                        "with the raw error")
    p.add_argument("--retry-max-attempts", type=int, default=3,
                   help="supervised retry budget: failed dispatches one "
                        "request may ride before it stops being retried")
    p.add_argument("--probe-interval-ms", type=float, default=5000.0,
                   help="degradation ladder: quiet time before a recovery "
                        "probe climbs one rung back up")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="fraction of requests recorded into the /debug/trace "
                        "ring (0 disables tracing entirely; histograms on "
                        "/metrics stay on regardless)")
    p.add_argument("--trace-ring", type=int, default=256,
                   help="how many recent request/batch traces to retain")
    p.add_argument("--trace-dir", default=None,
                   help="directory for trace dumps (/debug/trace?save=1, "
                        "shutdown dump); also arms the device_profile hook "
                        "(VNSUM_PROFILE_DIR) so the first engine batch "
                        "captures an XLA device trace alongside")
    p.add_argument("--journal-dir", default=None,
                   help="durable serving: write-ahead request journal "
                        "directory (serve/journal.py). Every accepted "
                        "request is journaled before engine work; on "
                        "startup unfinished requests replay through the "
                        "supervised path and finished ones answer "
                        "GET /v1/requests/<id>")
    p.add_argument("--journal-fsync-ms", type=float, default=50.0,
                   help="group-commit fsync interval; every record is "
                        "flushed to the kernel regardless (SIGKILL-safe), "
                        "this only bounds the power-loss window")
    p.add_argument("--tenants", default=None,
                   help="multi-tenant QoS (serve/qos.py): comma-separated "
                        "name:weight:token_rate[:tier] declarations, e.g. "
                        "'interactive:8:0,batch:1:500:batch'. Requests pick "
                        "their tenant via the X-Tenant header (missing = "
                        "'default', unknown = typed 400). Arms weighted-"
                        "fair scheduling, token-rate quotas (typed 429 "
                        "QUOTA + Retry-After), and — with --inflight — "
                        "preemption of batch-tier slots for interactive "
                        "work")
    p.add_argument("--preempt-budget", type=int, default=16,
                   help="max lifetime preemptions per batch-tier request "
                        "before it becomes non-evictable (starvation bound; "
                        "billed per GANG for structured jobs — any member "
                        "at budget makes the whole group non-evictable)")
    p.add_argument("--no-gang-affinity", action="store_true",
                   help="disable the queue's gang-affinity pick (siblings "
                        "of one structured job no longer cluster into the "
                        "same slot generation; admission, membership "
                        "journaling, and whole-gang QoS stay on — this is "
                        "the bench A/B lever, not a gang kill-switch)")
    p.add_argument("--stream-heartbeat-s", type=float, default=15.0,
                   help="SSE keepalive: emit ': heartbeat' comment frames "
                        "after this much quiet so idle proxies keep the "
                        "connection; the write doubles as the disconnect "
                        "probe between segments (0 = off)")
    p.add_argument("--stream-idle-timeout-s", type=float, default=10.0,
                   help="bounded resume window: a streaming request whose "
                        "client disconnected (no pops, no Last-Event-ID "
                        "reattach) for this long is CANCELLED and its slot "
                        "reclaimed (0 = cancel immediately on disconnect, "
                        "no resume window)")
    p.add_argument("--slo", default=None,
                   help="declarative SLOs over rolling windows "
                        "(serve/slo.py): comma-separated name=value "
                        "objectives, e.g. 'ttft_p99=0.5,e2e_p99=30,"
                        "error_rate=0.01,availability=0.999'. Evaluated "
                        "with fast/slow burn rates; breaches render on "
                        "/healthz, /debug/slo, and the vnsum_serve_slo_* "
                        "gauges, and fire the flight recorder")
    p.add_argument("--slo-fast-s", type=float, default=60.0,
                   help="SLO fast burn window (also the window of the "
                        "per-tenant usage latency gauges)")
    p.add_argument("--slo-slow-s", type=float, default=600.0,
                   help="SLO slow burn window (also the rolling-metrics "
                        "horizon)")
    p.add_argument("--slo-burn-fast", type=float, default=10.0,
                   help="fast-window burn rate at/above which an objective "
                        "breaches (with the slow threshold also met)")
    p.add_argument("--slo-burn-slow", type=float, default=1.0,
                   help="slow-window burn rate the fast breach must be "
                        "sustained at (multi-window alert discipline)")
    p.add_argument("--flight-dir", default=None,
                   help="flight recorder (obs/recorder.py) dump directory: "
                        "anomalies (brownout entry, fatal failure, poison "
                        "quarantine, SLO fast-burn, SIGTERM drain) write "
                        "the typed-event ring here as "
                        "flight_<reason>_<utc-ms>_<n>.json. Unset = ring + "
                        "/debug/flightrecorder only, no dumps")
    p.add_argument("--flight-events", type=int, default=4096,
                   help="flight-recorder ring capacity (events)")
    p.add_argument("--no-watchdog", action="store_true",
                   help="disable hang/stall detection (serve/watchdog.py). "
                        "Debug lever only — without it a wedged dispatch "
                        "freezes the scheduler silently until every client "
                        "times out")
    p.add_argument("--watchdog-interval-s", type=float, default=0.5,
                   help="watchdog monitor cadence (detection latency adds "
                        "at most one interval on top of the exceeded "
                        "budget/deadline)")
    p.add_argument("--watchdog-stall-s", type=float, default=10.0,
                   help="heartbeat deadline for loop threads: a scheduler "
                        "loop quiet this long OUTSIDE a budgeted dispatch "
                        "is a lock-classified stall (escalates to "
                        "seal-and-exit; helper threads get 6x this)")
    p.add_argument("--watchdog-dispatch-budget-s", type=float, default=30.0,
                   help="base wall-clock budget per engine dispatch; the "
                        "token-derived term is added on top, and a "
                        "dispatch past its budget is declared HUNG "
                        "(riders resolve typed, the scheduler thread is "
                        "replaced)")
    p.add_argument("--watchdog-dispatch-per-token-ms", type=float,
                   default=10.0,
                   help="per-token addition to the dispatch budget "
                        "(prompt + decode-ceiling tokens), so big batches "
                        "earn proportionally longer budgets instead of "
                        "tripping a one-size timeout")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="graceful-shutdown drain budget before queued and "
                        "in-flight requests are shed typed")
    # hermetic load/chaos knobs: give the fake backend the device-dispatch
    # latency shape so kills land mid-prefill/mid-decode instead of between
    # instantaneous calls (scripts/chaos_soak.py sets these)
    p.add_argument("--fake-batch-overhead-ms", type=float, default=0.0,
                   help="fake backend: fixed per-dispatch latency")
    p.add_argument("--fake-per-prompt-ms", type=float, default=0.0,
                   help="fake backend: marginal per-prompt latency")
    p.add_argument("--fake-segment-overhead-ms", type=float, default=0.0,
                   help="fake backend: per-decode-segment latency (the "
                        "in-flight chaos/QoS soaks need segments that take "
                        "real time so kills and preemptions land mid-decode)")
    p.add_argument("--fake-per-step-ms", type=float, default=0.0,
                   help="fake backend: per-decode-step latency (both paths)")
    p.add_argument("--fake-segment-words", type=int, default=8,
                   help="fake backend: words a slot-loop segment retires "
                        "per row (smaller = more segment boundaries — the "
                        "churn soak needs decodes that span many segments "
                        "so disconnect cancels land mid-decode)")
    args = p.parse_args(argv)

    if args.fused_segments < 1:
        p.error(f"--fused-segments {args.fused_segments} must be >= 1")
    if args.fused_segments > 1 and not args.inflight:
        p.error("--fused-segments > 1 requires --inflight (it is the slot "
                "loop's dispatch-fusing knob)")
    cache_blocks = 0 if args.no_prefix_cache else args.cache_blocks
    mesh = None
    if args.mesh:
        if args.backend != "tpu":
            p.error("--mesh requires --backend tpu")
        import jax

        from ..parallel.mesh import mesh_from_spec

        try:
            # make_mesh validates axis sizes against the device count and
            # raises with the offending shape; surface it as a CLI error
            # (with the live device count) instead of a traceback
            mesh = mesh_from_spec(args.mesh)
        # lint-allow[swallowed-exception]: p.error raises SystemExit(2) — the CLI-error path, nothing to resolve
        except ValueError as e:
            p.error(f"--mesh {args.mesh!r}: {e} "
                    f"(jax.device_count()={jax.device_count()})")
    if args.backend == "tpu":
        from ..models import MODEL_REGISTRY

        backend = get_backend(
            "tpu", model_config=MODEL_REGISTRY[args.model](),
            batch_size=args.max_batch,
            max_new_tokens=args.max_new_tokens,
            generation=GenerationConfig(spec_k=args.spec_k),
            cache_blocks=cache_blocks,
            cache_block_tokens=args.cache_block_tokens,
            mesh=mesh,
        )
    elif args.backend == "ollama":
        backend = get_backend("ollama", model=args.model)
    elif args.backend == "hf":
        backend = get_backend("hf", model_name_or_path=args.model)
    else:
        # the fake backend's synthetic cache blocks count whitespace words;
        # same budget flag, so hermetic dev servers exercise hit/evict paths
        backend = get_backend(
            "fake", spec_k=args.spec_k, prefix_cache_blocks=cache_blocks,
            batch_overhead_s=args.fake_batch_overhead_ms / 1000.0,
            per_prompt_s=args.fake_per_prompt_ms / 1000.0,
            segment_overhead_s=args.fake_segment_overhead_ms / 1000.0,
            per_step_s=args.fake_per_step_ms / 1000.0,
            segment_words=args.fake_segment_words,
        )

    tenants = None
    if args.tenants:
        from .qos import TenantTable, parse_tenant_specs

        try:
            tenants = TenantTable(parse_tenant_specs(args.tenants))
        # lint-allow[swallowed-exception]: p.error raises SystemExit(2) — the CLI-error path, nothing to resolve
        except ValueError as e:
            p.error(f"--tenants {args.tenants!r}: {e}")

    if args.slo:
        from .slo import parse_slo_spec

        try:
            parse_slo_spec(args.slo)  # validate at the CLI boundary
        # lint-allow[swallowed-exception]: p.error raises SystemExit(2) — the CLI-error path, nothing to resolve
        except ValueError as e:
            p.error(f"--slo {args.slo!r}: {e}")
        if args.slo_fast_s >= args.slo_slow_s:
            # the engine would raise the same complaint inside ServeState
            # construction — surface it as a clean CLI error instead
            p.error(
                f"--slo-fast-s {args.slo_fast_s} must be shorter than "
                f"--slo-slow-s {args.slo_slow_s}"
            )

    supervisor = None
    if not args.no_supervise:
        from .supervisor import EngineSupervisor, RetryPolicy

        supervisor = EngineSupervisor(
            RetryPolicy(max_attempts=args.retry_max_attempts),
            probe_interval_s=args.probe_interval_ms / 1000.0,
        )
    state = ServeState(
        backend,
        supervisor=supervisor,
        supervise=not args.no_supervise,
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        max_queue_depth=args.max_queue,
        max_queued_tokens=args.max_queued_tokens,
        default_deadline_s=(
            args.default_deadline_ms / 1000.0
            if args.default_deadline_ms else None
        ),
        default_spec_k=args.spec_k,
        trace_sample=args.trace_sample,
        trace_ring=args.trace_ring,
        trace_dir=args.trace_dir,
        inflight=args.inflight,
        slots=args.slots,
        slot_prompt_tokens=args.slot_prompt_tokens,
        fused_segments=args.fused_segments,
        journal_dir=args.journal_dir,
        journal_fsync_s=args.journal_fsync_ms / 1000.0,
        mesh=mesh,
        tenants=tenants,
        stream_heartbeat_s=args.stream_heartbeat_s,
        stream_idle_timeout_s=args.stream_idle_timeout_s,
        slo=args.slo,
        slo_fast_s=args.slo_fast_s,
        slo_slow_s=args.slo_slow_s,
        slo_burn_fast=args.slo_burn_fast,
        slo_burn_slow=args.slo_burn_slow,
        flight_dir=args.flight_dir,
        flight_events=args.flight_events,
        watchdog=not args.no_watchdog,
        watchdog_interval_s=args.watchdog_interval_s,
        watchdog_stall_s=args.watchdog_stall_s,
        watchdog_dispatch_base_s=args.watchdog_dispatch_budget_s,
        watchdog_dispatch_per_token_s=(
            args.watchdog_dispatch_per_token_ms / 1000.0
        ),
    )
    if args.inflight:
        state.scheduler.preempt_budget = max(args.preempt_budget, 1)
    if args.no_gang_affinity:
        state.scheduler.queue.gang_affinity = False
    # crash recovery BEFORE accepting new traffic: unfinished journaled
    # requests re-enqueue (the scheduler thread is already live, so replay
    # dispatch overlaps server bring-up)
    replayed = state.replay_journal()
    if replayed:
        logger.info("replaying %d journaled request(s) from %s",
                    replayed, args.journal_dir)
    server = make_server(state, args.host, args.port)

    # SIGTERM/SIGINT: drain, seal, exit 0 — an interrupted server must not
    # die mid-batch with the journal unsealed. The handler runs ON the main
    # thread inside serve_forever's poll loop, and shutdown() BLOCKS until
    # that loop exits — calling it inline would deadlock, so it runs on a
    # helper thread and the handler returns immediately.
    import signal

    def _graceful(signum, frame):
        logger.info("signal %d: draining and sealing the journal", signum)
        import threading

        threading.Thread(target=server.shutdown, daemon=True).start()

    def _stacks_on_demand(signum, frame):
        # SIGUSR1: the manual twin of the watchdog's automatic stall dump —
        # `kill -USR1 <pid>` when the server LOOKS wedged writes every
        # thread's stack to --flight-dir (or logs it with nowhere to write).
        # Runs in the main thread's signal trampoline: snapshotting is
        # read-only and allocation-light, safe even mid-wedge
        from ..core.artifacts import atomic_write_json
        from .watchdog import snapshot_stacks

        stacks = snapshot_stacks()
        if args.flight_dir:
            import pathlib

            path = pathlib.Path(args.flight_dir) / (
                f"watchdog_sigusr1_{int(time.time() * 1000)}.json"
            )
            try:
                atomic_write_json(path, {
                    "reason": "sigusr1", "dumped_wall": time.time(),
                    "stacks": stacks,
                })
                logger.warning("SIGUSR1: wrote stack dump %s", path)
                return
            # lint-allow[swallowed-exception]: the log fallback below IS the answer — an unwritable flight dir must not crash the signal trampoline
            except OSError:
                logger.exception("SIGUSR1 stack dump failed; logging")
        for t in stacks:
            logger.warning("SIGUSR1 stack [%s]:\n%s", t["name"],
                           "\n".join(t["stack"]))

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
        if hasattr(signal, "SIGUSR1"):
            signal.signal(signal.SIGUSR1, _stacks_on_demand)
    # lint-allow[swallowed-exception]: no request exists yet to resolve — logging that the embedding caller keeps signal ownership IS the handling
    except ValueError:
        # not the main thread (embedded/test use): the caller owns lifecycle
        logger.debug("not installing signal handlers off the main thread")

    logger.info(
        "serving on http://%s:%d/ (backend=%s max_batch=%d max_wait=%.0fms)",
        args.host, args.port, backend.name, args.max_batch, args.max_wait_ms,
    )
    try:
        server.serve_forever()
    # lint-allow[swallowed-exception]: Ctrl-C IS the shutdown request; the finally below drains the queue and resolves every future
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        # drain within the budget (overrun sheds typed), then seal+close
        # the journal so the next start sees a clean ledger
        state.close(drain_timeout_s=args.drain_timeout_s)
        if state.obs is not None and args.trace_dir:
            p = save_timestamped_trace(
                state.obs.chrome_trace(), args.trace_dir, "serve"
            )
            logger.info("wrote shutdown trace dump %s", p)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
