"""Serving observability: counters, histograms, rolling gauges + Prometheus
text export.

Three consumption surfaces off one locked data structure:

- GET /metrics renders the Prometheus text format — counters/gauges plus
  fixed-bucket histograms (``_bucket``/``_sum``/``_count``) for queue wait,
  TTFT, end-to-end latency, batch occupancy, and accepted-drafts-per-step
  (`obs/histogram.py`);
- snapshot() returns a core.results.ServingStats so run records and the
  serving benchmark embed the same numbers the scrape endpoint reports;
- histograms_snapshot() exposes the bucket state with bucket-derived
  p50/p95/p99, which `scripts/bench_serving.py` / `scripts/bench_spec_ab.py`
  write into their BENCH_*.json instead of bare means.

Metric registry: every exported metric is declared ONCE in the `_reg(...)`
block below — rendering takes its HELP/TYPE text from the registry, and
`metric_names()` feeds `scripts/check_metrics_doc.py`, the CI lint that
fails when a registered metric is missing from the README observability
table. Registration lines keep literal string names so the lint can parse
this file without importing it.

Emission sites for the registry entries: request/shed/batch counters and all
histograms are observed by `serve/scheduler.py` (observe_submit via the
queue's on_admit hook, observe_shed, observe_batch, observe_request);
queue_depth/queued_tokens gauges are read from the live RequestQueue at
scrape time by `serve/server.py`.
"""
from __future__ import annotations

from ..analysis.sanitizers import make_lock
from ..core.results import ServeRequestRecord, ServingStats
from ..obs.histogram import (
    ACCEPT_BUCKETS,
    E2E_BUCKETS_S,
    Histogram,
    OCCUPANCY_BUCKETS,
    SCRAPE_BUCKETS_S,
    TTFT_BUCKETS_S,
    WAIT_BUCKETS_S,
)
from ..obs.telemetry import Rolling
from ..obs.window import WindowedCounter, WindowedHistogram
from .queue import ShedReason
from .usage import TenantLabelRegistry, UsageLedger

_PREFIX = "vnsum_serve_"
_METRICS: dict[str, tuple[str, str]] = {}  # short name -> (type, help)


def _reg(name: str, typ: str, help_: str) -> str:
    _METRICS[name] = (typ, help_)
    return name


# -- the ONE metric registry (names literal for the CI doc lint) -------------
_reg("requests_total", "counter", "requests admitted to the queue")
_reg("requests_completed_total", "counter", "requests answered")
_reg("requests_errored_total", "counter", "requests failed in the engine")
_reg("requests_shed_total", "counter", "requests shed, by reason")
_reg("batches_total", "counter", "engine batches dispatched")
_reg("engine_seconds_total", "counter",
     "wall-clock seconds spent inside backend.generate")
_reg("queue_wait_seconds_total", "counter",
     "total seconds requests spent queued before dispatch")
_reg("prompt_tokens_total", "counter", "prompt tokens admitted")
_reg("generated_tokens_total", "counter", "tokens generated")
_reg("tokens_per_second", "gauge",
     "cumulative (prompt+generated) tokens / engine second")
_reg("tokens_per_second_rolling", "gauge",
     "generated tokens / engine second over the last 256 batches")
_reg("spec_draft_tokens_total", "counter",
     "tokens proposed by the speculative drafter")
_reg("spec_accepted_tokens_total", "counter",
     "drafted tokens the model accepted at verification")
_reg("spec_acceptance_rate", "gauge",
     "cumulative accepted / drafted tokens (0 when spec is off)")
_reg("spec_acceptance_rolling", "gauge",
     "accepted / drafted tokens over the last 256 requests")
_reg("cache_hit_tokens_total", "counter",
     "prompt tokens whose prefill was served from the prefix KV cache")
_reg("cache_hit_rate", "gauge",
     "cumulative cache-hit tokens / prompt tokens (0 when the cache is off)")
_reg("cache_evictions_total", "counter",
     "prefix-cache blocks evicted (LRU under the block budget)")
_reg("cache_blocks_used", "gauge",
     "prefix-cache blocks currently allocated")
_reg("cache_blocks_total", "gauge", "prefix-cache block budget")
_reg("inflight_segments_total", "counter",
     "decode segments dispatched by the in-flight slot loop")
_reg("inflight_refills_total", "counter",
     "requests admitted into a running decode batch at a segment boundary")
_reg("inflight_fused_dispatches_total", "counter",
     "fused slot-loop dispatches by the in-flight scheduler (each covers "
     "up to --fused-segments on-device decode segments; equals "
     "inflight_segments_total at N=1)")
_reg("inflight_fused_segments", "histogram",
     "on-device decode segments retired per fused slot-loop dispatch "
     "(the on-device all-rows-done stop reports fewer than the "
     "configured N on early exit)")
_reg("slots_total", "gauge",
     "decode slots of the in-flight loop (scrape-time; in-flight mode only)")
_reg("slots_busy", "gauge",
     "decode slots occupied at scrape (in-flight mode only)")
_reg("mesh_devices", "gauge",
     "devices in the serving mesh (scrape-time; absent = single-chip)")
_reg("mesh_data_parallel", "gauge",
     "serving mesh data-axis size (DP replicas; batch rows shard over it)")
_reg("mesh_model_parallel", "gauge",
     "serving mesh model-axis size (TP degree; heads/hidden shard over it)")
_reg("mesh_replica_occupancy", "gauge",
     "busy in-flight slots per DP replica at scrape (in-flight mode only)")
_reg("fault_failures_total", "counter",
     "classified engine dispatch failures, by failure class")
_reg("fault_retries_total", "counter",
     "request retries scheduled by the supervisor")
_reg("fault_bisects_total", "counter",
     "batch bisection splits performed to quarantine a poison request")
_reg("fault_quarantined_total", "counter",
     "requests failed with RequestFailed(poison) after bisection")
_reg("fault_backoff_seconds_total", "counter",
     "total seconds the supervisor spent in retry backoff")
_reg("degraded_rung", "gauge",
     "current degradation-ladder rung (0=healthy .. 4=brownout; scrape-time)")
_reg("degraded_steps_total", "counter",
     "degradation-ladder step-downs (resource-failure strikes)")
_reg("degraded_recoveries_total", "counter",
     "degradation-ladder step-ups (recovery probes that passed)")
_reg("qos_tenants", "gauge",
     "tenants declared in the QoS table (scrape-time; absent = no table)")
_reg("qos_requests_total", "counter",
     "requests admitted, by tenant (QoS table mode only)")
_reg("qos_quota_sheds_total", "counter",
     "typed QUOTA sheds (token-rate bucket dry), by tenant")
_reg("qos_bucket_tokens", "gauge",
     "token-rate bucket level at scrape, by tenant (rate-limited tenants)")
_reg("qos_preemptions_total", "counter",
     "batch-tier slot evictions performed for interactive work")
_reg("qos_requeues_total", "counter",
     "preempted requests re-admitted through the queue")
# -- structured jobs (serve/gang.py): gang-scheduled fan-out
_reg("gang_admitted_total", "counter",
     "structured jobs (gangs) admitted — one per fan-out request through "
     "the request-level admission gate")
_reg("gang_members_total", "counter",
     "fan-out children recorded into gang groups")
_reg("gang_affinity_picks_total", "counter",
     "take-path batches where the gang-affinity pick co-scheduled two or "
     "more siblings of one gang into the same generation")
_reg("gang_preemptions_total", "counter",
     "whole-gang slot evictions (group-granular QoS preemption — a gang "
     "is never half-evicted)")
_reg("gang_partial_total", "counter",
     "gangs degraded to a partial result (a POISON member was dropped "
     "from the reduce)")
_reg("gang_active", "gauge",
     "live structured-job groups in the gang registry (scrape-time)")
_reg("stream_requests_total", "counter",
     "requests served with SSE streaming (stream=true)")
_reg("stream_events_total", "counter",
     "SSE events written to streaming responses (deltas + progress + done)")
_reg("stream_active", "gauge",
     "streaming responses open right now")
_reg("cancel_requests_total", "counter",
     "requests terminally cancelled, by lifecycle stage at cancel")
_reg("cancel_disconnects_total", "counter",
     "cancellations triggered by client disconnect / idle-consumer timeout "
     "(vs an explicit DELETE)")
_reg("stream_backpressure_coalesced_total", "counter",
     "pending stream events collapsed by the bounded channel's "
     "coalesce-on-full (slow consumer backpressure)")
_reg("stream_resumes_total", "counter",
     "streaming reconnects served via Last-Event-ID (snapshot + continue)")
_reg("stream_heartbeats_total", "counter",
     "SSE keepalive heartbeat comment frames written")
_reg("cache_pinned_blocks", "gauge",
     "prefix-cache blocks pinned by live matches at scrape (leak probe: "
     "returns to 0 when no batch is in flight)")
_reg("journal_records_total", "counter",
     "write-ahead journal records appended (accept/start/complete/failed)")
_reg("journal_appended_bytes_total", "counter",
     "bytes appended to the write-ahead journal")
_reg("journal_fsyncs_total", "counter",
     "group-commit fsyncs issued by the journal")
_reg("journal_rotations_total", "counter",
     "journal segment rotations (size-triggered)")
_reg("journal_torn_records_total", "counter",
     "CRC-rejected torn/corrupt records dropped at recovery")
_reg("journal_replayed_total", "counter",
     "journaled requests re-enqueued by startup replay")
_reg("journal_replay_seconds_total", "counter",
     "wall-clock seconds spent re-enqueueing journaled requests")
_reg("journal_pending", "gauge",
     "journaled requests not yet COMPLETE or typed FAILED (scrape-time)")
# -- SLO engine (serve/slo.py): declarative objectives over the rolling
# windows, evaluated per objective with fast/slow burn rates
_reg("slo_compliance", "gauge",
     "fraction of the objective's window meeting its target, by objective")
_reg("slo_error_budget_remaining", "gauge",
     "unburned fraction of the objective's error budget over the slow "
     "window (0 = fully burned), by objective")
_reg("slo_burn_rate", "gauge",
     "error-budget burn rate (1.0 = burning exactly the budget), by "
     "objective and window (fast/slow)")
_reg("slo_breached", "gauge",
     "1 while any objective's fast AND slow burn rates exceed the breach "
     "thresholds, else 0")
_reg("slo_breaches_total", "counter",
     "objective breach transitions (edge-triggered; each fires the flight "
     "recorder)")
# -- per-tenant usage ledger (serve/usage.py): labels pass through the
# capped TenantLabelRegistry, so cardinality is bounded by construction
_reg("usage_requests_total", "counter", "requests admitted, by tenant")
_reg("usage_completed_total", "counter", "requests answered ok, by tenant")
_reg("usage_errors_total", "counter", "requests failed, by tenant")
_reg("usage_sheds_total", "counter", "requests shed, by tenant")
_reg("usage_cancels_total", "counter",
     "requests terminally cancelled, by tenant")
_reg("usage_preemptions_total", "counter",
     "slot evictions suffered, by tenant")
_reg("usage_requeues_total", "counter",
     "preempted requests re-admitted, by tenant")
_reg("usage_prompt_tokens_total", "counter", "prompt tokens, by tenant")
_reg("usage_generated_tokens_total", "counter",
     "generated tokens, by tenant")
_reg("usage_cached_tokens_total", "counter",
     "prompt tokens served from the prefix cache (the tenant's cache "
     "savings), by tenant")
_reg("usage_ttft_p99_seconds", "gauge",
     "anchored TTFT p99 over the fast window, by tenant")
_reg("usage_e2e_p99_seconds", "gauge",
     "end-to-end latency p99 over the fast window, by tenant")
_reg("usage_queue_wait_p99_seconds", "gauge",
     "queue-wait p99 over the fast window, by tenant")
_reg("usage_tenants_overflowed", "gauge",
     "distinct tenant names collapsed into the 'other' overflow label by "
     "the capped registry (cardinality pressure probe)")
# -- watchdog (serve/watchdog.py): hang/stall detection + recovery
_reg("watchdog_stalls_total", "counter",
     "stalls declared by the watchdog, by classification (dispatch = a "
     "dispatch past its token-derived budget, lock = a loop thread wedged "
     "outside the engine, helper = a helper thread went quiet)")
_reg("watchdog_recoveries_total", "counter",
     "wedged-dispatch recoveries completed (riders resolved typed HUNG or "
     "requeued, scheduler thread replaced)")
_reg("watchdog_hung_dispatches_total", "counter",
     "engine dispatches declared HUNG (past their wall-clock budget)")
_reg("watchdog_heartbeat_age_seconds", "gauge",
     "seconds since each registered thread's last heartbeat, by thread "
     "(scrape-time; mid-dispatch threads legitimately age until the "
     "dispatch ticket ends)")
# -- flight recorder (obs/recorder.py)
_reg("recorder_events_total", "counter",
     "typed lifecycle events appended to the flight-recorder ring")
_reg("recorder_events_dropped_total", "counter",
     "flight-recorder events evicted by the bounded ring")
_reg("recorder_dumps_total", "counter",
     "anomaly-triggered flight-recorder dumps written")
# -- scrape self-observation (satellite: /metrics cost made observable)
_reg("scrape_seconds", "histogram",
     "wall-clock cost of rendering /metrics (state is snapshotted under "
     "the metrics lock, rendered outside it; each scrape reports the "
     "distribution up to and including the PREVIOUS one)")
_reg("queue_depth", "gauge", "requests currently queued")
_reg("queued_tokens", "gauge",
     "billable (uncached) prompt-token estimate currently queued")
_reg("queue_wait_seconds", "histogram",
     "queue wait (submit -> engine dispatch)")
_reg("ttft_seconds", "histogram",
     "time to first token (submit -> end of the batch's prefill phase); "
     "observed only for requests whose batch emitted a prefill anchor, so "
     "counts can trail e2e_seconds when tracing is off")
_reg("e2e_seconds", "histogram",
     "end-to-end request latency (submit -> completion)")
_reg("batch_occupancy", "histogram", "engine batch occupancy at dispatch")
_reg("slot_occupancy", "histogram",
     "busy slots per in-flight decode segment")
_reg("spec_accepted_per_step", "histogram",
     "accepted draft tokens per verify step, per request")
# -- replica-fleet router (serve/router.py): the front-door process that
# fans requests out to N engine workers. Rendered by RouterMetrics from the
# same registry so the README doc-lint covers the fleet surface too
_reg("router_workers", "gauge",
     "engine workers configured behind the router")
_reg("router_workers_up", "gauge",
     "workers currently marked up (routable) by the probe loop")
_reg("router_requests_total", "counter",
     "requests proxied to each worker, by worker")
_reg("router_failovers_total", "counter",
     "journaled requests replayed onto survivors after a worker died or "
     "sealed (exit 86), by source worker")
_reg("router_markdowns_total", "counter",
     "worker mark-down transitions (probe-failure / SLO-burn hysteresis), "
     "by worker")
_reg("router_markups_total", "counter",
     "worker mark-up transitions (probes recovered), by worker")
_reg("router_restarts_total", "counter",
     "worker process restarts performed by the router (crash recovery + "
     "rolling deploys), by worker")
_reg("router_probe_seconds", "gauge",
     "latency of the most recent readiness probe, by worker")
_reg("router_sheds_total", "counter",
     "requests shed at the router front door, by reason")
# -- metrics/SLO federation (serve/federation.py): the router scrapes each
# worker's JSON snapshot on a cadence and re-exports fleet rollups —
# counters summed, histograms merged via Histogram.merge_from, gauges kept
# per worker under the bounded worker label
_reg("federation_scrapes_total", "counter",
     "worker snapshot scrapes completed by the router's federation loop, "
     "by worker")
_reg("federation_scrape_errors_total", "counter",
     "worker snapshot scrapes that failed (unreachable worker, bad "
     "payload, mismatched histogram ladder), by worker")
_reg("federation_scrape_seconds", "histogram",
     "wall-clock cost of one worker snapshot scrape (HTTP round trip + "
     "parse + fold)")
_reg("federation_staleness_seconds", "gauge",
     "age of the freshest good snapshot held for each worker, by worker "
     "(grows while a worker is unreachable)")
_reg("federation_clock_offset_seconds", "gauge",
     "estimated worker-monotonic minus router-monotonic clock offset "
     "(probe RTT midpoint method), by worker — the correction the merged "
     "/debug/trace applies")
_reg("fleet_requests_total", "counter",
     "requests admitted across the fleet (workers' requests_total summed "
     "at the last federation scrape)")
_reg("fleet_requests_completed_total", "counter",
     "requests answered across the fleet (summed rollup)")
_reg("fleet_requests_errored_total", "counter",
     "requests failed in engines across the fleet (summed rollup)")
_reg("fleet_generated_tokens_total", "counter",
     "tokens generated across the fleet (summed rollup)")
_reg("fleet_e2e_seconds", "histogram",
     "end-to-end request latency across the fleet (worker histograms "
     "merged bucket-wise at the last federation scrape)")
_reg("fleet_ttft_seconds", "histogram",
     "time to first token across the fleet (merged rollup; anchored "
     "observations only, same honesty rule as the worker series)")
_reg("fleet_queue_depth", "gauge",
     "requests queued on each worker at its last snapshot, by worker")
_reg("fleet_worker_up", "gauge",
     "1 while the router's probe loop marks the worker routable, else 0, "
     "by worker")
_reg("fleet_degraded_rung", "gauge",
     "each worker's degradation-ladder rung at its last snapshot, by "
     "worker")
_reg("fleet_slo_burn_fast", "gauge",
     "each worker's worst fast-window SLO burn rate at its last snapshot, "
     "by worker (the per-worker burn attribution behind fleet /debug/slo)")
_reg("fleet_slo_breached", "gauge",
     "1 while the worker's own SLO engine reports a breach, else 0, by "
     "worker")
_reg("fleet_incidents_total", "counter",
     "correlated incident bundles minted by the router, by trigger reason")


def metric_names(full: bool = True) -> list[str]:
    """Registered metric names (prefixed by default) — the doc-lint surface."""
    return [(_PREFIX + n if full else n) for n in _METRICS]


class ServeMetrics:
    """Aggregate counters + histograms; observe_* methods are called from the
    scheduler thread and the HTTP handler threads, so everything locks.

    Histograms and rolling windows are always on — a handful of integer adds
    per REQUEST (never per token), which is why they need no sampling gate;
    the pricier per-span tracing lives in obs.ObsHub behind --trace-sample.
    """

    def __init__(self, windowed: bool = True, horizon_s: float = 600.0,
                 sub_windows: int = 60, tenant_labels=None,
                 clock=None) -> None:
        import time as _time

        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("serve.metrics")
        self._clock = clock or _time.monotonic
        self._stats = ServingStats()            # guarded by: _lock
        self._hists = {                         # guarded by: _lock
            "queue_wait_seconds": Histogram(WAIT_BUCKETS_S),
            "ttft_seconds": Histogram(TTFT_BUCKETS_S),
            "e2e_seconds": Histogram(E2E_BUCKETS_S),
            "batch_occupancy": Histogram(OCCUPANCY_BUCKETS),
            "slot_occupancy": Histogram(OCCUPANCY_BUCKETS),
            "inflight_fused_segments": Histogram(OCCUPANCY_BUCKETS),
            "spec_accepted_per_step": Histogram(ACCEPT_BUCKETS),
        }
        self._rolling_accept = Rolling(256)     # guarded by: _lock
        self._rolling_tps = Rolling(256)        # guarded by: _lock
        # the capped label funnel every dynamically-labeled series routes
        # through; constructed even with windowed=False (the qos labels use
        # it too). Seed it with declared tenants via seed_tenants() so a
        # table tenant can never lose its label to earlier hostile names
        self.tenant_labels = tenant_labels or TenantLabelRegistry()
        # rolling windows (obs/window.py): the SLO engine's and the usage
        # ledger's substrate. windowed=False (bench all-off arm) constructs
        # none of it — the observe paths then pay only `is None` checks
        self._win: dict[str, WindowedHistogram] | None = None  # guarded by: _lock
        self._win_counts: WindowedCounter | None = None        # guarded by: _lock
        self.usage: UsageLedger | None = None                  # guarded by: _lock
        if windowed:
            kw = dict(horizon_s=horizon_s, sub_windows=sub_windows,
                      clock=self._clock)
            self._win = {
                "queue_wait_seconds": WindowedHistogram(WAIT_BUCKETS_S, **kw),
                "ttft_seconds": WindowedHistogram(TTFT_BUCKETS_S, **kw),
                "e2e_seconds": WindowedHistogram(E2E_BUCKETS_S, **kw),
            }
            self._win_counts = WindowedCounter(**kw)
            self.usage = UsageLedger(registry=self.tenant_labels,
                                     horizon_s=horizon_s,
                                     sub_windows=sub_windows,
                                     clock=self._clock)
        # scrape self-observation: each render times itself and observes
        # here AFTER releasing the lock for the render proper, so a scrape
        # reports the distribution up to and including the previous one
        self._scrape_hist = Histogram(SCRAPE_BUCKETS_S)  # guarded by: _lock
        # window the per-tenant latency gauges report over (the SLO fast
        # window; ServeState aligns it with --slo-fast-s)
        self.usage_window_s = 60.0

    def seed_tenants(self, names) -> None:
        """Reserve registry labels for declared tenants (the --tenants
        table) ahead of any traffic — unconditionally (`track`), so a
        declared tenant's series can never collapse into `other`."""
        with self._lock:
            for name in names:
                self.tenant_labels.track(name)

    # -- observation hooks ----------------------------------------------

    def observe_submit(self, n: int = 1, tenant: str = "") -> None:
        with self._lock:
            self._stats.submitted += n
            if self.usage is not None:
                self.usage.observe_submit(tenant, n)

    def observe_shed(self, reason: ShedReason, n: int = 1,
                     tenant: str = "") -> None:
        with self._lock:
            key = reason.value
            self._stats.shed[key] = self._stats.shed.get(key, 0) + n
            if self._win_counts is not None:
                self._win_counts.add("shed", n)
            if self.usage is not None:
                self.usage.observe_shed(tenant, n)

    def observe_batch(self, occupancy: int, engine_s: float,
                      gen_tokens: int = 0) -> None:
        with self._lock:
            self._stats.batches += 1
            self._stats.batch_occupancy_sum += occupancy
            self._stats.engine_seconds += engine_s
            self._hists["batch_occupancy"].observe(occupancy)
            self._rolling_tps.add(gen_tokens, engine_s)

    def observe_segment(self, live: int, seg_s: float,
                        gen_tokens: int = 0,
                        device_segments: int = 1) -> None:
        """One in-flight decode dispatch: slot occupancy, engine residency,
        and the tokens it retired (feeds the rolling tokens/s gauge the way
        observe_batch does for batch dispatches). ``device_segments`` is
        how many on-device segment boundaries the dispatch covered —
        segments_total counts those (device cadence) while
        fused_dispatches counts host round trips, so the two series
        diverge exactly by the fusing win."""
        with self._lock:
            n = max(int(device_segments), 1)
            self._stats.segments += n
            self._stats.fused_dispatches += 1
            self._stats.engine_seconds += seg_s
            self._hists["slot_occupancy"].observe(live)
            self._hists["inflight_fused_segments"].observe(n)
            self._rolling_tps.add(gen_tokens, seg_s)

    def observe_refill(self, n: int = 1) -> None:
        """Requests admitted into a RUNNING decode batch at a boundary."""
        with self._lock:
            self._stats.refills += n

    # -- fault-tolerance hooks (serve/supervisor.py consumers) -----------

    def observe_failure(self, failure_class: str) -> None:
        """One classified engine dispatch failure (pre-recovery: a retried
        batch counts here once per failed attempt, while requests_errored
        counts only terminal per-request outcomes)."""
        with self._lock:
            f = self._stats.failures
            f[failure_class] = f.get(failure_class, 0) + 1

    def observe_retry(self, n: int = 1) -> None:
        with self._lock:
            self._stats.retries += n

    def observe_bisect(self) -> None:
        with self._lock:
            self._stats.bisects += 1

    def observe_quarantine(self, n: int = 1) -> None:
        with self._lock:
            self._stats.quarantined += n

    def observe_backoff(self, seconds: float) -> None:
        with self._lock:
            self._stats.backoff_seconds += seconds

    # -- QoS / streaming hooks (serve/qos.py + serve/stream.py) -----------

    def observe_tenant_request(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            t = self._stats.tenant_requests
            t[tenant] = t.get(tenant, 0) + n

    def observe_quota_shed(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            q = self._stats.quota_sheds
            q[tenant] = q.get(tenant, 0) + n

    def observe_preemption(self, n: int = 1, tenant: str = "") -> None:
        with self._lock:
            self._stats.preemptions += n
            if self.usage is not None:
                self.usage.observe_preemption(tenant, n)

    def observe_requeue(self, n: int = 1, tenant: str = "") -> None:
        with self._lock:
            self._stats.requeues += n
            if self.usage is not None:
                self.usage.observe_requeue(tenant, n)

    # -- structured jobs (serve/gang.py) ----------------------------------

    def observe_gang_admitted(self, n: int = 1) -> None:
        with self._lock:
            self._stats.gang_admitted += n

    def observe_gang_members(self, n: int = 1) -> None:
        with self._lock:
            self._stats.gang_members += n

    def observe_gang_affinity_pick(self, n: int = 1) -> None:
        """One take-path batch in which the affinity pick co-scheduled >=2
        siblings of a gang (counted once per gang per batch)."""
        with self._lock:
            self._stats.gang_affinity_picks += n

    def observe_gang_preemption(self, n: int = 1) -> None:
        with self._lock:
            self._stats.gang_preemptions += n

    def observe_gang_partial(self, n: int = 1) -> None:
        with self._lock:
            self._stats.gang_partials += n

    def observe_stream_request(self, n: int = 1) -> None:
        with self._lock:
            self._stats.stream_requests += n

    def observe_stream_events(self, n: int = 1) -> None:
        with self._lock:
            self._stats.stream_events += n

    def observe_stream_open(self, delta: int) -> None:
        """+1 when an SSE response opens, -1 when it closes — the
        streams_open gauge."""
        with self._lock:
            self._stats.streams_open = max(
                self._stats.streams_open + delta, 0
            )

    # -- cancellation / stream-hardening hooks ----------------------------

    def observe_cancel(self, stage: str, n: int = 1,
                       tenant: str = "") -> None:
        """One terminal cancellation, keyed by the lifecycle stage it
        landed in: queued (never dispatched), dispatched (one-shot batch in
        the engine), resident (evicted from a decode slot)."""
        with self._lock:
            c = self._stats.cancelled
            c[stage] = c.get(stage, 0) + n
            if self.usage is not None:
                self.usage.observe_cancel(tenant, n)

    def observe_cancel_disconnect(self, n: int = 1) -> None:
        with self._lock:
            self._stats.cancel_disconnects += n

    def observe_stream_coalesced(self, n: int = 1) -> None:
        """Pending events collapsed by a bounded StreamChannel hitting its
        maxsize — the backpressure signal a wedged consumer emits."""
        with self._lock:
            self._stats.stream_coalesced += n

    def observe_stream_resume(self, n: int = 1) -> None:
        with self._lock:
            self._stats.stream_resumes += n

    def observe_stream_heartbeat(self, n: int = 1) -> None:
        with self._lock:
            self._stats.stream_heartbeats += n

    def observe_degraded(self, down: bool) -> None:
        """One ladder transition: down=True is a step-down (strike
        threshold), False a recovery step-up."""
        with self._lock:
            if down:
                self._stats.degraded_steps += 1
            else:
                self._stats.degraded_recoveries += 1

    def observe_request(self, rec: ServeRequestRecord,
                        tenant: str = "") -> None:
        with self._lock:
            if rec.status == "ok":
                self._stats.completed += 1
            elif rec.status == "error":
                self._stats.errors += 1
            self._stats.queue_wait_seconds += rec.queue_wait_s
            self._stats.prompt_tokens += rec.prompt_tokens
            self._stats.generated_tokens += rec.generated_tokens
            self._stats.draft_tokens += rec.draft_tokens
            self._stats.accepted_tokens += rec.accepted_tokens
            self._stats.cache_hit_tokens += rec.cached_prompt_tokens
            self._hists["queue_wait_seconds"].observe(rec.queue_wait_s)
            if rec.status == "ok":
                # only anchored TTFT (a real prefill-end timestamp from the
                # batch trace) enters the histogram: the unanchored fallback
                # equals e2e and would silently poison the quantiles
                if rec.ttft_anchored:
                    self._hists["ttft_seconds"].observe(rec.ttft_s)
                self._hists["e2e_seconds"].observe(rec.total_s)
            if rec.draft_tokens:
                self._rolling_accept.add(rec.accepted_tokens, rec.draft_tokens)
            if rec.spec_steps:
                self._hists["spec_accepted_per_step"].observe(
                    rec.accepted_tokens / rec.spec_steps
                )
            # rolling windows + usage ledger (the SLO/usage substrate):
            # same honesty rules as the cumulative histograms, plus the
            # trace_id as the per-bucket exemplar so a bad windowed p99
            # links straight to /debug/trace
            if self._win is not None:
                self._win["queue_wait_seconds"].observe(
                    rec.queue_wait_s, exemplar=rec.trace_id
                )
                if rec.status == "ok":
                    self._win_counts.add("completed")
                    if rec.ttft_anchored:
                        self._win["ttft_seconds"].observe(
                            rec.ttft_s, exemplar=rec.trace_id
                        )
                    self._win["e2e_seconds"].observe(
                        rec.total_s, exemplar=rec.trace_id
                    )
                elif rec.status == "error":
                    self._win_counts.add("errors")
            if self.usage is not None:
                self.usage.observe_request(tenant, rec)

    # -- export ----------------------------------------------------------

    def snapshot(self) -> ServingStats:
        import copy

        with self._lock:
            return copy.deepcopy(self._stats)

    def histograms_snapshot(self) -> dict:
        """{name: {buckets, sum, count, p50, p95, p99}} for bench JSON."""
        with self._lock:
            return {k: h.to_dict() for k, h in self._hists.items()}

    def federation_snapshot(self) -> dict:
        """The scrape payload for the fleet router's federation loop
        (``GET /debug/obs/snapshot``): the counters it sums and the raw
        histogram state it merges, snapshotted in ONE lock hold so a
        rollup never ships a count that disagrees with its buckets. Raw
        ``state_dict`` (bounds + counts), not the render format — the
        router folds with Histogram.merge_from."""
        with self._lock:
            s = self._stats
            return {
                "counters": {
                    "requests_total": s.submitted,
                    "requests_completed_total": s.completed,
                    "requests_errored_total": s.errors,
                    "generated_tokens_total": s.generated_tokens,
                },
                "hists": {
                    "e2e_seconds": self._hists["e2e_seconds"].state_dict(),
                    "ttft_seconds": self._hists["ttft_seconds"].state_dict(),
                },
            }

    def now(self) -> float:
        """The metrics' own clock — callers taking multiple window views
        that must agree (the SLO engine's fast+slow reads) resolve ONE
        moment here and pass it to each."""
        return self._clock()

    def window_view(self, window_s: float | None = None,
                    now: float | None = None) -> dict | None:
        """Merged rolling-window state for the SLO engine (serve/slo.py):
        {"hists": {name: Histogram}, "counts": {...}, "exemplars": {...}}
        over the most recent ``window_s`` — or None when windows are off
        (windowed=False). One lock hold AND one resolved ``now`` for the
        whole view, so a burn-rate evaluation never mixes two moments (a
        sub-window boundary between two merges would otherwise give the
        latency hists and the error counts different window sets)."""
        with self._lock:
            if self._win is None:
                return None
            if now is None:
                now = self._clock()
            return {
                "hists": {
                    k: wh.merged(window_s, now)
                    for k, wh in self._win.items()
                },
                "counts": self._win_counts.totals(window_s, now),
                "exemplars": {
                    k: wh.exemplars(window_s, now)
                    for k, wh in self._win.items()
                },
            }

    def usage_snapshot(self, window_s: float | None = None) -> dict | None:
        """Per-tenant ledger for ``GET /v1/usage`` (None when windows are
        off). Latency quantiles cover ``window_s`` (default: the whole
        horizon)."""
        with self._lock:
            if self.usage is None:
                return None
            return self.usage.snapshot(window_s)

    def render_prometheus(self, queue_depth: int | None = None,
                          queued_tokens: int | None = None,
                          cache_stats: dict | None = None,
                          slot_state: tuple[int, int] | None = None,
                          degraded_rung: int | None = None,
                          journal_stats: dict | None = None,
                          mesh_state: dict | None = None,
                          qos_state: dict | None = None,
                          gang_state: dict | None = None,
                          slo_state: dict | None = None,
                          recorder_stats: dict | None = None,
                          watchdog_stats: dict | None = None,
                          exemplars: bool = False) -> str:
        """``cache_stats`` is the backend's prefix_cache_stats() snapshot
        (evictions / blocks_used / blocks_total), read at scrape time like
        the queue gauges — the serving layer never mirrors pool state.
        ``mesh_state`` is ServeState.mesh_state() (devices / data / model,
        plus replica_occupancy when the in-flight loop is live).
        ``qos_state`` is TenantTable.stats() (per-tenant config + bucket
        levels), read from the live table at scrape time — absent entirely
        on servers without a tenant table. ``slo_state`` is
        SloEngine.export_state() (absent without --slo); ``recorder_stats``
        the FlightRecorder's stats_dict (absent without a recorder).
        ``exemplars=True`` suffixes the latency buckets with OpenMetrics
        exemplars — callers must only set it for scrapes that NEGOTIATED
        the OpenMetrics format (the classic text-format parser rejects a
        trailing ``# {...}`` after a sample and drops the whole scrape).

        Scrape discipline (the /metrics cost satellite): ALL owned state is
        snapshotted in ONE lock hold, the text renders outside it, and the
        render's own wall clock lands in the scrape_seconds histogram — so
        an expensive scrape shows up in the very surface it serves and can
        never stall the observe hot paths for its render phase."""
        import copy

        t_scrape = self._clock()
        # one lock acquisition for stats AND histograms: a scrape must not
        # see a histogram count that disagrees with the counters it shipped
        # with
        with self._lock:
            s = copy.deepcopy(self._stats)
            hists = {k: h.copy() for k, h in self._hists.items()}
            rolling_accept = self._rolling_accept.rate()
            rolling_tps = self._rolling_tps.rate()
            scrape_hist = self._scrape_hist.copy()
            # recent-window exemplars ride the CUMULATIVE latency buckets:
            # recent trace ids are the useful breadcrumbs, and the windowed
            # structures are where they live
            bucket_exemplars = (
                {k: self._win[k].exemplars()
                 for k in ("ttft_seconds", "e2e_seconds")}
                if exemplars and self._win is not None else {}
            )
            usage_rows = (
                self.usage.snapshot(self.usage_window_s)
                if self.usage is not None else None
            )
            labels_overflowed = self.tenant_labels.overflowed
        lines = []

        def simple(name, value):
            typ, help_ = _METRICS[name]  # KeyError = unregistered metric
            lines.append(f"# HELP {_PREFIX}{name} {help_}")
            lines.append(f"# TYPE {_PREFIX}{name} {typ}")
            lines.append(f"{_PREFIX}{name} {value}")

        simple("requests_total", s.submitted)
        simple("requests_completed_total", s.completed)
        simple("requests_errored_total", s.errors)
        typ, help_ = _METRICS["requests_shed_total"]
        lines.append(f"# HELP {_PREFIX}requests_shed_total {help_}")
        lines.append(f"# TYPE {_PREFIX}requests_shed_total {typ}")
        for reason in ShedReason:
            lines.append(
                f'{_PREFIX}requests_shed_total{{reason="{reason.value}"}} '
                f"{s.shed.get(reason.value, 0)}"
            )
        simple("batches_total", s.batches)
        # NOTE batch_occupancy_sum is deliberately NOT a standalone series:
        # the batch_occupancy histogram's _sum sample carries the identical
        # number, and the duplicate sample name made Prometheus (and the
        # strict OpenMetrics parser) reject the whole scrape
        simple("engine_seconds_total", round(s.engine_seconds, 6))
        simple("queue_wait_seconds_total", round(s.queue_wait_seconds, 6))
        simple("prompt_tokens_total", s.prompt_tokens)
        simple("generated_tokens_total", s.generated_tokens)
        simple("tokens_per_second", round(s.tokens_per_second, 3))
        simple("tokens_per_second_rolling", round(rolling_tps, 3))
        simple("spec_draft_tokens_total", s.draft_tokens)
        simple("spec_accepted_tokens_total", s.accepted_tokens)
        simple("spec_acceptance_rate", round(s.acceptance_rate, 6))
        simple("spec_acceptance_rolling", round(rolling_accept, 6))
        simple("cache_hit_tokens_total", s.cache_hit_tokens)
        simple("cache_hit_rate", round(s.cache_hit_rate, 6))
        simple("inflight_segments_total", s.segments)
        simple("inflight_refills_total", s.refills)
        simple("inflight_fused_dispatches_total", s.fused_dispatches)
        typ, help_ = _METRICS["fault_failures_total"]
        lines.append(f"# HELP {_PREFIX}fault_failures_total {help_}")
        lines.append(f"# TYPE {_PREFIX}fault_failures_total {typ}")
        # stable label set: every failure class renders, zeros included, so
        # dashboards see series before the first failure of a class
        from .supervisor import FailureClass

        for cls in FailureClass:
            lines.append(
                f'{_PREFIX}fault_failures_total{{class="{cls.value}"}} '
                f"{s.failures.get(cls.value, 0)}"
            )
        simple("fault_retries_total", s.retries)
        simple("fault_bisects_total", s.bisects)
        simple("fault_quarantined_total", s.quarantined)
        simple("fault_backoff_seconds_total", round(s.backoff_seconds, 6))
        simple("degraded_steps_total", s.degraded_steps)
        simple("degraded_recoveries_total", s.degraded_recoveries)
        simple("qos_preemptions_total", s.preemptions)
        simple("qos_requeues_total", s.requeues)
        simple("gang_admitted_total", s.gang_admitted)
        simple("gang_members_total", s.gang_members)
        simple("gang_affinity_picks_total", s.gang_affinity_picks)
        simple("gang_preemptions_total", s.gang_preemptions)
        simple("gang_partial_total", s.gang_partials)
        if gang_state is not None:
            # read from the live GangRegistry at scrape time, like the
            # queue gauges — the metrics layer never mirrors group state
            simple("gang_active", gang_state.get("active", 0))
        simple("stream_requests_total", s.stream_requests)
        simple("stream_events_total", s.stream_events)
        simple("stream_active", s.streams_open)
        typ, help_ = _METRICS["cancel_requests_total"]
        lines.append(f"# HELP {_PREFIX}cancel_requests_total {help_}")
        lines.append(f"# TYPE {_PREFIX}cancel_requests_total {typ}")
        # stable label set: every lifecycle stage renders, zeros included,
        # so dashboards see series before the first cancel of a stage
        for stage in ("queued", "dispatched", "resident"):
            lines.append(
                f'{_PREFIX}cancel_requests_total{{stage="{stage}"}} '
                f"{s.cancelled.get(stage, 0)}"
            )
        simple("cancel_disconnects_total", s.cancel_disconnects)
        simple("stream_backpressure_coalesced_total", s.stream_coalesced)
        simple("stream_resumes_total", s.stream_resumes)
        simple("stream_heartbeats_total", s.stream_heartbeats)
        headered: set = set()

        def labeled(name, label_val, value):
            # THE tenant-labeled emission path: every dynamic tenant label
            # funnels through the capped registry (the metric-label-
            # cardinality lint pins this), so hostile names collapse into
            # "other" instead of growing the scrape. Header dedup is a set
            # probe, not a scan of the whole exposition — the usage block
            # emits up to 13 series per tenant on the very path the
            # scrape_seconds self-metric is watching
            typ, help_ = _METRICS[name]
            if name not in headered:
                headered.add(name)
                lines.append(f"# HELP {_PREFIX}{name} {help_}")
                lines.append(f"# TYPE {_PREFIX}{name} {typ}")
            lines.append(
                f'{_PREFIX}{name}'
                f'{{tenant="{self.tenant_labels.canonical(label_val, touch=False)}"}} '
                f'{value}'
            )

        if qos_state is not None:
            # per-tenant series, read from the live TenantTable at scrape
            # time like the queue gauges — the metrics layer never mirrors
            # bucket state. Label sets are the DECLARED tenants, so
            # dashboards see every series from the first scrape. Loops are
            # FAMILY-outer, tenant-inner: OpenMetrics requires one family's
            # samples to be contiguous (a tenant-outer loop interleaves
            # families and a strict OM parser drops the whole scrape)
            simple("qos_tenants", len(qos_state))
            qos_tenants = sorted(qos_state)
            for tenant in qos_tenants:
                labeled("qos_requests_total", tenant,
                        s.tenant_requests.get(tenant, 0))
            for tenant in qos_tenants:
                labeled("qos_quota_sheds_total", tenant,
                        s.quota_sheds.get(tenant, 0))
            for tenant in qos_tenants:
                if qos_state[tenant].get("bucket_tokens") is not None:
                    labeled("qos_bucket_tokens", tenant,
                            qos_state[tenant]["bucket_tokens"])
        if usage_rows is not None:
            # the per-tenant usage ledger (serve/usage.py): keys are already
            # canonical (the ledger itself is registry-keyed), counters are
            # monotone, latency gauges cover the fast window. Family-outer
            # like the qos block (OM sample contiguity)
            simple("usage_tenants_overflowed", labels_overflowed)
            for family, value_of in (
                ("usage_requests_total", lambda u: u["requests"]),
                ("usage_completed_total", lambda u: u["completed"]),
                ("usage_errors_total", lambda u: u["errors"]),
                ("usage_sheds_total", lambda u: u["sheds"]),
                ("usage_cancels_total", lambda u: u["cancels"]),
                ("usage_preemptions_total", lambda u: u["preemptions"]),
                ("usage_requeues_total", lambda u: u["requeues"]),
                ("usage_prompt_tokens_total", lambda u: u["prompt_tokens"]),
                ("usage_generated_tokens_total",
                 lambda u: u["generated_tokens"]),
                ("usage_cached_tokens_total",
                 lambda u: u["cached_tokens_saved"]),
                ("usage_ttft_p99_seconds", lambda u: u["ttft"]["p99_s"]),
                ("usage_e2e_p99_seconds", lambda u: u["e2e"]["p99_s"]),
                ("usage_queue_wait_p99_seconds",
                 lambda u: u["queue_wait"]["p99_s"]),
            ):
                for tenant in sorted(usage_rows):
                    labeled(family, tenant, value_of(usage_rows[tenant]))
        if slo_state is not None:
            # SLO engine gauges (serve/slo.py), computed from the rolling
            # windows at evaluation time and handed in at scrape time like
            # every other live-subsystem state
            simple("slo_breached", 1 if slo_state.get("breached") else 0)
            simple("slo_breaches_total", slo_state.get("breaches_total", 0))

            def slo_labeled(metric, objective, value, extra=""):
                typ, help_ = _METRICS[metric]
                if metric not in headered:
                    headered.add(metric)
                    lines.append(f"# HELP {_PREFIX}{metric} {help_}")
                    lines.append(f"# TYPE {_PREFIX}{metric} {typ}")
                # lint-allow[metric-label-cardinality]: objective names are parse-time-validated --slo spec tokens — a bounded, operator-declared set, not request-derived
                lines.append(f'{_PREFIX}{metric}{{objective="{objective}"'
                             f'{extra}}} {value}')

            # family-outer like the tenant blocks (OM sample contiguity);
            # both burn windows share one family, so they ride one loop
            objective_names = sorted(slo_state.get("objectives", {}))
            for name in objective_names:
                slo_labeled("slo_compliance", name,
                            round(slo_state["objectives"][name]["compliance"],
                                  6))
            for name in objective_names:
                slo_labeled(
                    "slo_error_budget_remaining", name,
                    round(slo_state["objectives"][name]["budget_remaining"],
                          6))
            for name in objective_names:
                obj = slo_state["objectives"][name]
                slo_labeled("slo_burn_rate", name,
                            round(obj["burn_fast"], 6), ',window="fast"')
                slo_labeled("slo_burn_rate", name,
                            round(obj["burn_slow"], 6), ',window="slow"')
        if recorder_stats is not None:
            simple("recorder_events_total", recorder_stats.get("events", 0))
            simple("recorder_events_dropped_total",
                   recorder_stats.get("dropped", 0))
            simple("recorder_dumps_total", recorder_stats.get("dumps", 0))
        if watchdog_stats is not None:
            # read from the live Watchdog at scrape time, like the queue
            # gauges — the metrics layer never mirrors liveness state.
            # Stable stall-kind label set, zeros included, so dashboards
            # see every series before the first (hopefully never) stall
            from .watchdog import STALL_KINDS

            typ, help_ = _METRICS["watchdog_stalls_total"]
            lines.append(f"# HELP {_PREFIX}watchdog_stalls_total {help_}")
            lines.append(f"# TYPE {_PREFIX}watchdog_stalls_total {typ}")
            stalls = watchdog_stats.get("stalls", {})
            for kind in STALL_KINDS:
                lines.append(
                    # lint-allow[metric-label-cardinality]: STALL_KINDS is the watchdog's code-declared classification vocabulary — a fixed 3-entry tuple, never request-derived
                    f'{_PREFIX}watchdog_stalls_total{{kind="{kind}"}} '
                    f"{stalls.get(kind, 0)}"
                )
            simple("watchdog_recoveries_total",
                   watchdog_stats.get("recoveries", 0))
            simple("watchdog_hung_dispatches_total",
                   watchdog_stats.get("hung_dispatches", 0))
            ages = watchdog_stats.get("heartbeat_ages", {})
            if ages:
                typ, help_ = _METRICS["watchdog_heartbeat_age_seconds"]
                lines.append(
                    f"# HELP {_PREFIX}watchdog_heartbeat_age_seconds {help_}"
                )
                lines.append(
                    f"# TYPE {_PREFIX}watchdog_heartbeat_age_seconds {typ}"
                )
                for name in sorted(ages):
                    lines.append(
                        f'{_PREFIX}watchdog_heartbeat_age_seconds'
                        # lint-allow[metric-label-cardinality]: thread labels are registration-time code literals ("scheduler", "slo-monitor") — a bounded, operator-invisible set, never request-derived
                        f'{{thread="{name}"}} {ages[name]}'
                    )
        if degraded_rung is not None:
            # read from the live supervisor at scrape time, like the queue
            # gauges — the metrics layer never mirrors ladder state
            simple("degraded_rung", degraded_rung)
        if slot_state is not None:
            # (total, busy) read from the live slot loop at scrape time,
            # like the queue gauges — the metrics layer never mirrors it
            simple("slots_total", slot_state[0])
            simple("slots_busy", slot_state[1])
        if mesh_state is not None:
            # serving-mesh topology, read from the live ServeState at
            # scrape time — absent entirely on single-chip servers
            simple("mesh_devices", mesh_state.get("devices", 1))
            simple("mesh_data_parallel", mesh_state.get("data", 1))
            simple("mesh_model_parallel", mesh_state.get("model", 1))
            if "replica_occupancy" in mesh_state:
                simple("mesh_replica_occupancy",
                       round(mesh_state["replica_occupancy"], 3))
        if journal_stats is not None:
            # read from the live RequestJournal at scrape time, like the
            # queue gauges — the metrics layer never mirrors ledger state
            simple("journal_records_total", journal_stats.get("records", 0))
            simple("journal_appended_bytes_total",
                   journal_stats.get("appended_bytes", 0))
            simple("journal_fsyncs_total", journal_stats.get("fsyncs", 0))
            simple("journal_rotations_total",
                   journal_stats.get("rotations", 0))
            simple("journal_torn_records_total",
                   journal_stats.get("torn_records", 0))
            simple("journal_replayed_total",
                   journal_stats.get("replayed", 0))
            simple("journal_replay_seconds_total",
                   journal_stats.get("replay_seconds", 0.0))
            simple("journal_pending", journal_stats.get("pending", 0))
        if cache_stats is not None:
            simple("cache_evictions_total", cache_stats.get("evictions", 0))
            simple("cache_blocks_used", cache_stats.get("blocks_used", 0))
            simple("cache_blocks_total", cache_stats.get("blocks_total", 0))
            if "pinned_blocks" in cache_stats:
                # live-match pin count (radix introspection): the chaos
                # soaks assert this returns to baseline after churn — a
                # non-zero value with no batch in flight is a pin leak
                simple("cache_pinned_blocks", cache_stats["pinned_blocks"])
        if queue_depth is not None:
            simple("queue_depth", queue_depth)
        if queued_tokens is not None:
            simple("queued_tokens", queued_tokens)
        for name, h in hists.items():
            lines.extend(h.render(_PREFIX + name, _METRICS[name][1],
                                  bucket_exemplars.get(name)))
        lines.extend(scrape_hist.render(
            _PREFIX + "scrape_seconds", _METRICS["scrape_seconds"][1]
        ))
        if exemplars:
            # OpenMetrics family naming: a counter family's HELP/TYPE
            # metadata carries the name WITHOUT the _total suffix (samples
            # keep it) — the classic 0.0.4 rendering above uses the full
            # sample name, which a strict OM parser rejects, dropping the
            # whole exposition. Rewrite metadata lines only. Counters whose
            # OM family name cannot be expressed — no _total suffix, or a
            # stripped name that collides with another registered family
            # (queue_wait_seconds_total vs the queue_wait_seconds latency
            # histogram) — are demoted to `unknown`, the OM escape hatch
            # whose sample name equals its family name
            om = []
            for ln in lines:
                if ln.startswith("# "):
                    _hash, _, rest = ln.partition(" ")
                    kind, _, rest = rest.partition(" ")
                    name, _, tail = rest.partition(" ")
                    base = name[len(_PREFIX):]
                    if _METRICS.get(base, ("",))[0] == "counter":
                        stripped = base[: -len("_total")]
                        if base.endswith("_total") and stripped not in _METRICS:
                            name = _PREFIX + stripped
                        elif kind == "TYPE":
                            tail = "unknown"
                        ln = f"# {kind} {name} {tail}"
                om.append(ln)
            lines = om
        out = "\n".join(lines) + "\n"
        # self-observation AFTER the render: the cost just paid lands in
        # the NEXT scrape's scrape_seconds (one short lock hold, no render
        # work inside it)
        with self._lock:
            self._scrape_hist.observe(self._clock() - t_scrape)
        return out
