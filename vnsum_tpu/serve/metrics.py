"""Serving observability: thread-safe counters + Prometheus text export.

Two consumption surfaces off one data structure:
- GET /metrics renders the Prometheus text format (counters, gauges, and a
  cumulative histogram for queue wait), so a scrape loop sees queue wait,
  batch occupancy, time-in-engine, tokens/s, and shed counts per reason;
- snapshot() returns a core.results.ServingStats so run records and the
  serving benchmark embed the same numbers the scrape endpoint reports —
  one source of truth, two serializations.
"""
from __future__ import annotations

import threading

from ..core.results import ServeRequestRecord, ServingStats
from .queue import ShedReason

# cumulative histogram bucket upper bounds (seconds) for queue wait — spans
# sub-millisecond coalescing waits through multi-second overload backlogs
_WAIT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class ServeMetrics:
    """Aggregate counters; observe_* methods are called from the scheduler
    thread and the HTTP handler threads, so everything locks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats = ServingStats()
        self._wait_buckets = [0] * (len(_WAIT_BUCKETS) + 1)  # +inf tail

    # -- observation hooks ----------------------------------------------

    def observe_submit(self, n: int = 1) -> None:
        with self._lock:
            self._stats.submitted += n

    def observe_shed(self, reason: ShedReason, n: int = 1) -> None:
        with self._lock:
            key = reason.value
            self._stats.shed[key] = self._stats.shed.get(key, 0) + n

    def observe_batch(self, occupancy: int, engine_s: float) -> None:
        with self._lock:
            self._stats.batches += 1
            self._stats.batch_occupancy_sum += occupancy
            self._stats.engine_seconds += engine_s

    def observe_request(self, rec: ServeRequestRecord) -> None:
        with self._lock:
            if rec.status == "ok":
                self._stats.completed += 1
            elif rec.status == "error":
                self._stats.errors += 1
            self._stats.queue_wait_seconds += rec.queue_wait_s
            self._stats.prompt_tokens += rec.prompt_tokens
            self._stats.generated_tokens += rec.generated_tokens
            self._stats.draft_tokens += rec.draft_tokens
            self._stats.accepted_tokens += rec.accepted_tokens
            for i, ub in enumerate(_WAIT_BUCKETS):
                if rec.queue_wait_s <= ub:
                    self._wait_buckets[i] += 1
                    break
            else:
                self._wait_buckets[-1] += 1

    # -- export ----------------------------------------------------------

    def snapshot(self) -> ServingStats:
        import copy

        with self._lock:
            return copy.deepcopy(self._stats)

    def render_prometheus(self, queue_depth: int | None = None,
                          queued_tokens: int | None = None) -> str:
        import copy

        # one lock acquisition for stats AND buckets: a scrape must not see
        # a histogram count that disagrees with the counters it shipped with
        with self._lock:
            s = copy.deepcopy(self._stats)
            buckets = list(self._wait_buckets)
        lines = []

        def counter(name, value, help_, labels=""):
            lines.append(f"# HELP vnsum_serve_{name} {help_}")
            lines.append(f"# TYPE vnsum_serve_{name} counter")
            lines.append(f"vnsum_serve_{name}{labels} {value}")

        def gauge(name, value, help_):
            lines.append(f"# HELP vnsum_serve_{name} {help_}")
            lines.append(f"# TYPE vnsum_serve_{name} gauge")
            lines.append(f"vnsum_serve_{name} {value}")

        counter("requests_total", s.submitted, "requests admitted to the queue")
        counter("requests_completed_total", s.completed, "requests answered")
        counter("requests_errored_total", s.errors, "requests failed in the engine")
        lines.append("# HELP vnsum_serve_requests_shed_total requests shed, by reason")
        lines.append("# TYPE vnsum_serve_requests_shed_total counter")
        for reason in ShedReason:
            lines.append(
                f'vnsum_serve_requests_shed_total{{reason="{reason.value}"}} '
                f"{s.shed.get(reason.value, 0)}"
            )
        counter("batches_total", s.batches, "engine batches dispatched")
        counter("batch_occupancy_sum", s.batch_occupancy_sum,
                "sum of engine batch occupancies (avg = sum / batches_total)")
        counter("engine_seconds_total", round(s.engine_seconds, 6),
                "wall-clock seconds spent inside backend.generate")
        counter("queue_wait_seconds_total", round(s.queue_wait_seconds, 6),
                "total seconds requests spent queued before dispatch")
        counter("prompt_tokens_total", s.prompt_tokens, "prompt tokens admitted")
        counter("generated_tokens_total", s.generated_tokens, "tokens generated")
        gauge("tokens_per_second", round(s.tokens_per_second, 3),
              "cumulative (prompt+generated) tokens / engine second")
        counter("spec_draft_tokens_total", s.draft_tokens,
                "tokens proposed by the speculative drafter")
        counter("spec_accepted_tokens_total", s.accepted_tokens,
                "drafted tokens the model accepted at verification")
        gauge("spec_acceptance_rate", round(s.acceptance_rate, 6),
              "cumulative accepted / drafted tokens (0 when spec is off)")
        if queue_depth is not None:
            gauge("queue_depth", queue_depth, "requests currently queued")
        if queued_tokens is not None:
            gauge("queued_tokens", queued_tokens,
                  "prompt-token estimate currently queued")

        lines.append("# HELP vnsum_serve_queue_wait_seconds queue wait histogram")
        lines.append("# TYPE vnsum_serve_queue_wait_seconds histogram")
        cum = 0
        for ub, n in zip(_WAIT_BUCKETS, buckets):
            cum += n
            lines.append(
                f'vnsum_serve_queue_wait_seconds_bucket{{le="{ub}"}} {cum}'
            )
        cum += buckets[-1]
        lines.append(f'vnsum_serve_queue_wait_seconds_bucket{{le="+Inf"}} {cum}')
        lines.append(
            f"vnsum_serve_queue_wait_seconds_sum {round(s.queue_wait_seconds, 6)}"
        )
        lines.append(f"vnsum_serve_queue_wait_seconds_count {cum}")
        return "\n".join(lines) + "\n"
