"""Replica-fleet front door: one router process over N engine workers.

Everything through PR 15 — sharded decode, in-flight batching, QoS,
journal durability, watchdog liveness — lives in ONE process: one Python
runtime, one GIL, one blast radius. This module is the process half of
the scale-out story: a thin HTTP front door that owns **admission**,
**per-tenant accounting**, and the **journal** globally, and fans
``/v1/*`` requests out to N worker processes (serve/worker.py — each a
full single-process engine, FakeBackend for tests/bench, real backend
unchanged) over the exact HTTP surface that already exists. The fleet
layer adds topology; it does not fork the protocol.

Routing — tenant-sticky with cache affinity::

    key = cache_hint or tenant        # rendezvous (HRW) hash over UP workers
    fallback = least-loaded           # no key -> min in-flight

Rendezvous hashing ranks every worker per key, so a mark-down remaps only
the dead worker's keys — the radix-cache hit rates that justify
``cache_hint`` routing survive both the split across workers and a
failover (bench_serving's fleet phase holds the shared-prefix hit rate
within 10% of single-process).

Health — probe loop with mark-down/mark-up hysteresis: every worker is
probed on ``/readyz`` (routability: draining / browned-out / pre-replay
answer typed 503) plus the ``/healthz`` SLO verdict (a page-level burn
counts as a failed probe, so a worker burning its error budget browns out
of rotation before clients feel it). ``down_after`` consecutive failures
mark a worker down, ``up_after`` successes mark it back up; a dead
process (``poll() != None``) or connect refusal is an immediate strike.

Failover — journal handoff: the router journals every admitted request
(ACCEPT with the full replayable payload) *before* dispatch. When a
worker dies or seals (exit 86 = watchdog seal-and-exit), its non-terminal
rids replay onto survivors — inline while the client connection is still
attached (the proxy thread re-dispatches and the client never sees the
death), or from the probe loop for anything left behind. The same
machinery replays the router's OWN journal after a router restart. No
accepted request is lost; greedy replays are byte-identical
(scripts/chaos_soak.py --fleet SIGKILLs a worker mid-load to prove it).

Deploys — rolling drain-one-restart-one (``POST /admin/rolling-restart``):
each spawned worker is taken out of rotation, drained (SIGTERM -> queue
drain -> journal seal -> exit 0), restarted, and only returns to rotation
once its ``/readyz`` probes pass.

Streaming is the one surface the front door does not proxy yet
(``stream=true`` answers a typed 501): SSE pass-through needs chunked
relay plumbing, and a client that wants streams can speak to a worker
directly. Everything else — generate, summarize, poll, cancel, health,
metrics — routes.

Threading: one router lock (``make_lock("serve.router")``) guards the
worker table and admission counters; the journal keeps its own innermost
lock. Proxy I/O, probes, and handoffs all run outside the router lock —
the lock scopes bookkeeping, never a network round trip.
"""
from __future__ import annotations

import argparse
import http.client
import json
import shlex
import signal
import threading
import time
import zlib
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from ..analysis.sanitizers import make_lock
from ..core.logging import get_logger
from ..obs.recorder import FlightRecorder
from ..obs.trace import ObsHub
from .federation import FleetFederation, IncidentManager
from .journal import RequestJournal, aggregate_status
from .metrics import _METRICS, _PREFIX
from .server import (
    _BadRequest,
    _deadline_from,
    _gen_config_from,
    _number,
    _request_id,
)
from .usage import TenantLabelRegistry
from .watchdog import WATCHDOG_EXIT_CODE

logger = get_logger("vnsum.serve.router")

# front-door shed reasons (the router's own, rendered as
# vnsum_serve_router_sheds_total{reason=...}): queue_full mirrors the
# worker-side ShedReason value; shutdown is the draining front door;
# no_worker means zero routable workers; stream_unsupported is the typed
# 501 for SSE pass-through
_SHED_REASONS = ("queue_full", "shutdown", "no_worker", "stream_unsupported")


@dataclass
class _RouterRequest:
    """The journal-facing shape of one admitted prompt: just enough
    attribute surface for :func:`journal.request_payload` to build the
    same replayable ACCEPT record a worker would."""

    trace_id: str
    prompt: str
    max_new_tokens: int | None = None
    config: object | None = None
    reference: str | None = None
    cache_hint: str | None = None
    deadline: float | None = None
    tenant: str = ""
    tier: str = "interactive"
    approach: str | None = None
    journal_rid: str | None = None


class Worker:
    """One engine worker as the router sees it: endpoint + routing state.

    This is a record, not an actor: every mutable field below is written
    and read under the owning :class:`RouterState`'s lock (the worker
    itself holds none). ``handle`` is a
    :class:`~vnsum_tpu.serve.worker.WorkerHandle` when the router owns the
    process (--spawn-workers / rolling restarts), None for an external
    endpoint the router only routes to.
    """

    def __init__(self, name: str, host: str, port: int,
                 handle=None) -> None:
        self.name = name
        self.host = host
        self.port = port
        self.handle = handle
        # -- routing state (router-lock scope) --
        self.up = False
        self.draining = False
        self.inflight = 0
        self.fail_streak = 0
        self.ok_streak = 0
        self.last_probe_s = 0.0
        self.last_reason = "unprobed"
        self.last_markdown_reason = ""  # why the LAST mark-down happened
        self.last_restart = 0.0
        self.handed_off = False  # one monitor handoff per down transition
        # -- counters (router-lock scope; /metrics reads them) --
        self.requests = 0
        self.failovers = 0
        self.markdowns = 0
        self.markups = 0
        self.restarts = 0

    def row(self) -> dict:
        """The /healthz projection (caller holds the router lock)."""
        return {
            "name": self.name, "host": self.host, "port": self.port,
            "up": self.up, "draining": self.draining,
            "reason": self.last_reason, "inflight": self.inflight,
            "last_markdown_reason": self.last_markdown_reason,
            "requests": self.requests, "failovers": self.failovers,
            "markdowns": self.markdowns, "markups": self.markups,
            "restarts": self.restarts,
            "probe_s": round(self.last_probe_s, 6),
            "pid": self.handle.pid if self.handle is not None else None,
            "spawned": self.handle is not None,
        }


def request_body_from_payload(rid: str, payload: dict) -> tuple[str, dict, dict]:
    """Journal ACCEPT payload -> ``(path, body, headers)`` for re-dispatch
    over the worker ``/v1/*`` surface — the inverse of
    :func:`journal.request_payload` for everything HTTP can carry.
    ``eos_ids``/``spec_ngram`` never differ from engine defaults for
    HTTP-admitted requests, and the wall-clock deadline converts back to
    the *remaining* ``deadline_ms`` budget (the caller checks expiry
    first). Summarize payloads (marked by ``approach``) re-dispatch
    through ``/v1/summarize``; everything else through ``/v1/generate``."""
    body: dict = {"request_id": rid}
    if payload.get("max_new_tokens") is not None:
        body["max_new_tokens"] = payload["max_new_tokens"]
    deadline_unix = payload.get("deadline_unix")
    if deadline_unix is not None:
        body["deadline_ms"] = max(
            1, int((deadline_unix - time.time()) * 1000.0)
        )
    headers = {"X-Request-Id": rid}
    if payload.get("tenant"):
        headers["X-Tenant"] = payload["tenant"]
    approach = payload.get("approach")
    if approach:
        body["text"] = payload.get("prompt", "")
        body["approach"] = approach
        return "/v1/summarize", body, headers
    body["prompt"] = payload.get("prompt", "")
    cfg = payload.get("config") or {}
    for key in ("temperature", "top_k", "top_p", "seed", "spec_k"):
        if cfg.get(key) is not None:
            body[key] = cfg[key]
    if payload.get("reference") is not None:
        body["reference"] = payload["reference"]
    if payload.get("cache_hint") is not None:
        body["cache_hint"] = payload["cache_hint"]
    return "/v1/generate", body, headers


class _WorkerConns(threading.local):
    """Per-thread keep-alive sockets to workers (handler threads and the
    failover threads each keep their own, so no lock and no sharing)."""

    def __init__(self) -> None:
        self.conns: dict[tuple[str, int], http.client.HTTPConnection] = {}


class RouterState:
    """Front-door state: the worker table, probe loop, global journal,
    admission counters, and the failover machinery."""

    def __init__(
        self,
        workers: list[Worker],
        *,
        journal_dir: str | Path | None = None,
        journal_fsync_s: float = 0.05,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 2.0,
        down_after: int = 2,
        up_after: int = 1,
        max_inflight: int = 256,
        proxy_timeout_s: float = 120.0,
        default_deadline_s: float | None = None,
        tenants: dict[str, str] | None = None,
        restart_crashed: bool = True,
        restart_backoff_s: float = 1.0,
        probe_slo_burn: bool = True,
        federate: bool = True,
        federation_interval_s: float = 1.0,
        incident_dir: str | Path | None = None,
        incident_min_interval_s: float = 30.0,
        trace_ring: int = 256,
    ) -> None:
        self.workers = list(workers)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.down_after = int(down_after)
        self.up_after = int(up_after)
        self.max_inflight = int(max_inflight)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.default_deadline_s = default_deadline_s
        self.tenants = tenants  # name -> tier; None = single-class
        self.restart_crashed = bool(restart_crashed)
        self.restart_backoff_s = float(restart_backoff_s)
        self.probe_slo_burn = bool(probe_slo_burn)
        self.started_wall = time.time()
        self.started_monotonic = time.monotonic()
        # the GLOBAL request ledger: ACCEPT before dispatch, terminal from
        # the worker's answer — the handoff source for worker deaths AND
        # the replay source for router restarts. None = volatile routing
        self.journal: RequestJournal | None = None
        if journal_dir:
            self.journal = RequestJournal(
                journal_dir, fsync_interval_s=journal_fsync_s
            )
        # bounded worker-label registry: the fleet roster, seeded at
        # construction — every worker= label the router's /metrics emits
        # passes through canonical(), so an off-roster name can never mint
        # a new series (the metric-label-cardinality contract)
        self.worker_labels = TenantLabelRegistry(
            cap=max(64, 2 * len(self.workers) + 8),
            seed=[w.name for w in self.workers],
        )
        # the routing-decision ring: route / markdown / markup / failover /
        # handoff_replay / worker_restart / incident events — the router's
        # half of every incident bundle
        self.recorder = FlightRecorder(capacity=4096,
                                       directory=incident_dir)
        # router-side spans for every proxied request — the root of the
        # stitched fleet trace. sample=1.0: the proxy hop is a worker HTTP
        # round trip; a handful of span appends is noise against it
        self.obs = ObsHub(sample=1.0, ring=int(trace_ring))
        self.federation = (
            FleetFederation(self, interval_s=federation_interval_s)
            if federate else None
        )
        self.incidents = IncidentManager(
            self, self.federation, incident_dir,
            min_interval_s=incident_min_interval_s,
        )
        if self.federation is not None:
            self.federation.fast_burn_cb = (
                lambda detail: self.incidents.trigger("slo_fast_burn",
                                                      detail)
            )
        # lock-order: this lock is OUTER to the journal's (journal stays
        # innermost fleet-wide, same as under the queue lock in-process);
        # in practice every journal call here runs outside the router lock
        self._lock = make_lock("serve.router")
        self._inflight = 0                      # guarded by: _lock
        self._assigned: dict[str, str] = {}     # rid -> worker name  # guarded by: _lock
        self._claimed: set[str] = set()         # rids a failover path owns  # guarded by: _lock
        self._sheds: dict[str, int] = {}        # reason -> count  # guarded by: _lock
        self._tenant_requests: dict[str, int] = {}  # guarded by: _lock
        self._draining = False                  # guarded by: _lock
        self._rolling = False                   # guarded by: _lock
        self._replay_started = self.journal is None  # guarded by: _lock
        self._replay_done = self.journal is None     # guarded by: _lock
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self._conns = _WorkerConns()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start the probe loop (and, journal permitting, arm the startup
        replay — it fires from the probe loop once a worker is up)."""
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True
        )
        self._probe_thread.start()
        if self.federation is not None:
            self.federation.start()

    def close(self, drain_timeout_s: float = 30.0) -> None:
        """Graceful shutdown: stop admitting (typed 503), drain in-flight
        proxies (bounded), stop probing, drain every spawned worker
        (SIGTERM -> exit 0), seal + close the journal."""
        with self._lock:
            self._draining = True
        t_end = time.monotonic() + drain_timeout_s
        while time.monotonic() < t_end:
            with self._lock:
                busy = self._inflight
            if busy == 0:
                break
            time.sleep(0.02)
        self._stop.set()
        if self.federation is not None:
            self.federation.close()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10.0)
        for w in self.workers:
            if w.handle is not None and w.handle.alive:
                w.handle.sigterm()
        for w in self.workers:
            if w.handle is not None and w.handle.proc is not None:
                try:
                    rc = w.handle.wait_exit(drain_timeout_s)
                    logger.info("worker %s exited rc=%s", w.name, rc)
                # lint-allow[swallowed-exception]: a drain-timeout escalates to SIGKILL right below — the worker ends either way and shutdown proceeds
                except Exception:
                    logger.warning(
                        "worker %s ignored SIGTERM at router shutdown — "
                        "killing", w.name,
                    )
                    w.handle.sigkill()
                    w.handle.wait_exit(10.0)
        if self.journal is not None:
            self.journal.seal()
            self.journal.close()

    def readiness(self) -> tuple[bool, str]:
        """The router's own ``/readyz`` verdict, same typed contract as
        the worker's: draining / pre_replay / no_worker are "alive but do
        not route"."""
        with self._lock:
            if self._draining:
                return False, "draining"
            if not self._replay_done:
                return False, "pre_replay"
            if not any(w.up and not w.draining for w in self.workers):
                return False, "no_worker"
        return True, "ready"

    def wait_ready(self, timeout_s: float = 30.0) -> None:
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            ready, _ = self.readiness()
            if ready:
                return
            time.sleep(0.02)
        raise TimeoutError("router never became ready "
                           f"({self.readiness()[1]})")

    # -- routing -----------------------------------------------------------

    def _pick_locked(self, affinity: str | None,
                     exclude: set[str] | None = None) -> Worker | None:
        up = [w for w in self.workers if w.up and not w.draining]
        if exclude:
            spared = [w for w in up if w.name not in exclude]
            # only honor the exclusion when an alternative exists — with
            # one worker left, retrying it beats shedding outright
            if spared:
                up = spared
        if not up:
            return None
        if affinity:
            # rendezvous (highest-random-weight) hashing: every key ranks
            # every worker; a mark-down remaps only the lost worker's keys,
            # so cache affinity survives failovers
            return max(up, key=lambda w: zlib.crc32(
                f"{affinity}|{w.name}".encode()
            ))
        # least-loaded, tie-broken by lifetime count so idle-fleet traffic
        # round-robins instead of piling onto the first worker
        return min(up, key=lambda w: (w.inflight, w.requests))

    def pick(self, affinity: str | None = None,
             exclude: set[str] | None = None) -> Worker | None:
        with self._lock:
            return self._pick_locked(affinity, exclude)

    def shed(self, reason: str) -> None:
        with self._lock:
            self._sheds[reason] = self._sheds.get(reason, 0) + 1

    # -- health probing ----------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            for w in list(self.workers):
                self._probe_one(w)
            self._maybe_startup_replay()

    def _maybe_startup_replay(self) -> None:
        """Router-restart recovery: once any worker is routable, replay
        the router journal's unfinished ACCEPTs (claimed exactly once —
        take_unfinished is at-most-once per process)."""
        with self._lock:
            if self._replay_started:
                return
            if not any(w.up and not w.draining for w in self.workers):
                return
            self._replay_started = True
        threading.Thread(target=self._startup_replay,
                         name="router-replay", daemon=True).start()

    def _startup_replay(self) -> None:
        t0 = time.monotonic()
        entries = self.journal.take_unfinished()
        n = 0
        for entry in entries:
            n += self._redispatch(entry, exclude=None, source=None)
        self.journal.note_replay(n, time.monotonic() - t0)
        if entries:
            logger.info("router journal replay: re-dispatched %d of %d "
                        "unfinished request(s)", n, len(entries))
        with self._lock:
            self._replay_done = True

    def _probe_one(self, w: Worker) -> None:
        # a dead PROCESS is an immediate verdict — no hysteresis, the exit
        # code says whether the journal was sealed (0 / 86) or torn
        if w.handle is not None and w.handle.proc is not None:
            rc = w.handle.poll()
            if rc is not None:
                self._note_death(w, rc)
                return
        t0 = time.monotonic()
        ok = False
        reason = "unreachable"
        try:
            status, body = self._worker_http(
                w, "GET", "/readyz", timeout=self.probe_timeout_s
            )
            ok = status == 200
            if not ok:
                reason = (body or {}).get("reason", f"http:{status}")
            elif self.probe_slo_burn:
                fed = (self.federation.fresh_payload(w.name)
                       if self.federation is not None else None)
                if fed is not None:
                    # federation-fed markdown policy: the scrape loop
                    # already holds this worker's windowed SLO verdict —
                    # no second HTTP round trip per probe beat
                    if (fed.get("slo") or {}).get("breached"):
                        ok = False
                        reason = "slo_burn"
                else:
                    # no fresh federation sample (loop off, or the worker
                    # just joined): fall back to the /healthz verdict
                    hstatus, hbody = self._worker_http(
                        w, "GET", "/healthz", timeout=self.probe_timeout_s
                    )
                    slo = ((hbody or {}).get("slo")
                           if hstatus == 200 else None)
                    if isinstance(slo, str) and slo.startswith("BREACH"):
                        # the worker's own SLO verdict (slo.status_line()):
                        # a page-level burn browns the worker out of
                        # rotation before clients feel the tail
                        ok = False
                        reason = "slo_burn"
        # lint-allow[swallowed-exception]: ok stays False and the hysteresis below IS the resolution — a refused probe is a strike, not an error
        except OSError:
            pass
        dt = time.monotonic() - t0
        marked_down = marked_up = False
        with self._lock:
            w.last_probe_s = dt
            w.last_reason = reason if not ok else "ready"
            if ok:
                w.fail_streak = 0
                w.ok_streak += 1
                if not w.up and w.ok_streak >= self.up_after:
                    w.up = True
                    w.markups += 1
                    w.handed_off = False
                    marked_up = True
                    logger.info("worker %s marked UP", w.name)
            else:
                w.ok_streak = 0
                w.fail_streak += 1
                if w.up and w.fail_streak >= self.down_after:
                    w.up = False
                    w.markdowns += 1
                    w.last_markdown_reason = reason
                    marked_down = True
                    logger.warning("worker %s marked DOWN (%s)",
                                   w.name, reason)
        if marked_up:
            self.recorder.record("markup", worker=w.name)
        if marked_down:
            self.recorder.record("markdown", worker=w.name, reason=reason)
            self.incidents.trigger("markdown", detail=f"{w.name}: {reason}")
            self._spawn_handoff(w, reason)

    def _note_death(self, w: Worker, rc: int) -> None:
        reason = "sealed" if rc == WATCHDOG_EXIT_CODE else f"exit:{rc}"
        respawn = False
        with self._lock:
            was_up = w.up
            w.up = False
            w.ok_streak = 0
            w.fail_streak += 1
            w.last_reason = reason
            if was_up:
                w.markdowns += 1
                w.last_markdown_reason = reason
            need_handoff = not w.handed_off
            w.handed_off = True
            if (
                self.restart_crashed
                and not self._draining
                and not w.draining
                and time.monotonic() - w.last_restart
                > self.restart_backoff_s
            ):
                w.last_restart = time.monotonic()
                w.restarts += 1
                respawn = True
        if was_up:
            logger.warning("worker %s died (%s) — marked DOWN",
                           w.name, reason)
            self.recorder.record("markdown", worker=w.name, reason=reason)
            self.incidents.trigger("markdown", detail=f"{w.name}: {reason}")
        if need_handoff:
            self._spawn_handoff(w, reason)
        if respawn:
            # the respawned worker replays ITS journal before /readyz says
            # 200 (pre_replay), so it re-enters rotation fully recovered
            logger.info("respawning worker %s after %s", w.name, reason)
            self.recorder.record("worker_restart", worker=w.name,
                                 reason=reason)
            w.handle.start()

    # -- journal-handoff failover ------------------------------------------

    def _spawn_handoff(self, w: Worker, reason: str) -> None:
        if self.journal is None:
            return
        threading.Thread(
            target=self._handoff, args=(w, reason),
            name=f"handoff-{w.name}", daemon=True,
        ).start()

    def _handoff(self, worker: Worker, reason: str) -> int:
        """Replay every non-terminal rid assigned to a dead/sealed worker
        onto survivors. Claims under the lock so the inline proxy-thread
        failover and this sweep never double-dispatch one rid."""
        with self._lock:
            rids = [
                rid for rid, wn in self._assigned.items()
                if wn == worker.name and rid not in self._claimed
            ]
            self._claimed.update(rids)
        if rids:
            self.recorder.record("failover", worker=worker.name,
                                 reason=reason, rids=len(rids))
            self.incidents.trigger("failover",
                                   detail=f"{worker.name}: {reason} "
                                          f"({len(rids)} rid(s))")
        n = 0
        for rid in rids:
            entry = None
            for e in self.journal.lookup(rid):
                if e.rid == rid:
                    entry = e
                    break
            if entry is None or entry.terminal:
                with self._lock:
                    self._assigned.pop(rid, None)
                    self._claimed.discard(rid)
                continue
            n += self._redispatch(entry, exclude={worker.name},
                                  source=worker)
        if n:
            logger.info("handoff from %s (%s): %d request(s) replayed "
                        "onto survivors", worker.name, reason, n)
        return n

    def _redispatch(self, entry, exclude: set[str] | None,
                    source: Worker | None) -> int:
        """Re-POST one journaled ACCEPT onto a survivor; terminal-izes the
        ledger entry whatever happens (complete, typed shed, or typed
        failover failure). Returns 1 if the entry COMPLETEd."""
        rid = entry.rid
        payload = entry.payload
        deadline_unix = payload.get("deadline_unix")
        if deadline_unix is not None and time.time() >= deadline_unix:
            self.journal.fail(rid, "shed:deadline",
                              "expired before failover replay")
            self._release(rid)
            return 0
        path, body, headers = request_body_from_payload(rid, payload)
        # cross-process trace context on the replay hop, same as the
        # inline proxy's
        headers["X-Parent-Span"] = f"router:{rid}"
        mode = "handoff_replay" if source is not None else "journal_replay"
        # the POST-failover half of the stitched fleet trace: a NEW
        # router-side trace under the SAME base trace id as the original
        # dispatch, so the merged /debug/trace shows both halves of a
        # handed-off request inside one process group
        trace = (self.obs.start_request(rid.partition("#")[0])
                 if self.obs is not None else None)
        outcome = "error"
        try:
            affinity = (payload.get("cache_hint") or payload.get("tenant")
                        or None)
            tried = set(exclude or ())
            attempts = max(3, len(self.workers) + 1)
            last_detail = "no routable worker"
            for attempt in range(attempts):
                if (deadline_unix is not None
                        and time.time() >= deadline_unix):
                    last_detail = "deadline expired during failover"
                    break
                w = self.pick(affinity, exclude=tried)
                if w is None:
                    time.sleep(min(0.2, self.probe_interval_s))
                    continue
                with self._lock:
                    self._assigned[rid] = w.name
                    w.inflight += 1
                    w.requests += 1
                    if source is not None:
                        source.failovers += 1
                if source is not None:
                    source = None  # count the failover once, not per attempt
                self.recorder.record(mode, rid=rid, worker=w.name)
                self.journal.start(rid)
                t_req = time.monotonic()
                try:
                    status, resp = self._worker_http(
                        w, "POST", path, body=body, headers=headers,
                        timeout=self.proxy_timeout_s,
                    )
                # lint-allow[swallowed-exception]: resolved by the retry loop — the next attempt picks a survivor, and exhaustion terminal-izes the rid as failover:exhausted below
                except OSError as e:
                    if trace is not None:
                        trace.add(mode, t_req, time.monotonic() - t_req,
                                  worker=w.name, outcome="unreachable")
                    with self._lock:
                        w.inflight -= 1
                    tried.add(w.name)
                    last_detail = f"{w.name}: {e}"
                    continue
                if trace is not None:
                    trace.add(mode, t_req, time.monotonic() - t_req,
                              worker=w.name, status=status)
                with self._lock:
                    w.inflight -= 1
                if status == 200:
                    self._journal_success(rid, path, resp)
                    self._release(rid)
                    outcome = "ok"
                    return 1
                if status in (429, 503):
                    # a typed worker shed: back off and retry a (possibly
                    # different) survivor until attempts run out
                    tried = set(exclude or ())
                    last_detail = f"{w.name}: shed {status}"
                    time.sleep(min(0.2, self.probe_interval_s))
                    continue
                detail = (json.dumps(resp)[:200] if resp
                          else f"http {status}")
                self.journal.fail(rid, f"failover:http_{status}", detail)
                self._release(rid)
                return 0
            self.journal.fail(rid, "failover:exhausted", last_detail)
            self._release(rid)
            return 0
        finally:
            if self.obs is not None:
                self.obs.finish_request(trace, outcome)

    def _journal_success(self, rid: str, path: str, resp: dict | None) -> None:
        """Fold a worker 200 into the ledger for ONE single-prompt
        re-dispatch (the proxy path handles fan-out itself)."""
        if path == "/v1/summarize":
            text = (resp or {}).get("summary", "")
            gen = ((resp or {}).get("serving") or {}).get(
                "generated_tokens", 0
            )
            self.journal.complete(rid, text, gen)
            return
        comps = (resp or {}).get("completions") or []
        first = comps[0] if comps else {}
        self.journal.complete(
            rid, first.get("text", ""),
            (first.get("record") or {}).get("generated_tokens", 0),
        )

    def _release(self, rid: str) -> None:
        with self._lock:
            self._assigned.pop(rid, None)
            self._claimed.discard(rid)

    # -- worker I/O --------------------------------------------------------

    def _worker_http(self, w: Worker, method: str, path: str,
                     body: dict | None = None,
                     headers: dict | None = None,
                     timeout: float = 30.0):
        """One round trip to a worker over this thread's keep-alive
        socket -> (status, parsed-JSON-or-None). A stale keep-alive (the
        worker restarted between requests) gets ONE fresh-socket retry;
        a genuinely dead worker raises OSError to the caller's failover
        logic. Duplicate execution on the retry is safe: requests are
        rid-keyed and the engine is deterministic per payload."""
        key = (w.host, w.port)
        raw_body = json.dumps(body).encode() if body is not None else None
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        for fresh in (False, True):
            conn = None if fresh else self._conns.conns.get(key)
            if conn is None:
                conn = http.client.HTTPConnection(
                    w.host, w.port, timeout=timeout
                )
                self._conns.conns[key] = conn
            try:
                conn.timeout = timeout
                conn.request(method, path, body=raw_body, headers=hdrs)
                resp = conn.getresponse()
                raw = resp.read()
                try:
                    return resp.status, json.loads(raw) if raw else None
                # lint-allow[swallowed-exception]: a non-JSON body relays as None — callers branch on status
                except ValueError:
                    return resp.status, None
            except OSError:
                conn.close()
                self._conns.conns.pop(key, None)
                if fresh:
                    raise
        raise OSError("unreachable")  # pragma: no cover — loop always returns/raises

    # -- admission + accounting --------------------------------------------

    def admit(self, tenant: str) -> str | None:
        """Front-door admission: returns a typed shed reason, or None when
        admitted (caller MUST pair with :meth:`release_admission`)."""
        with self._lock:
            if self._draining:
                return "shutdown"
            if self._inflight >= self.max_inflight:
                return "queue_full"
            self._inflight += 1
            key = tenant or ""
            self._tenant_requests[key] = self._tenant_requests.get(key, 0) + 1
        return None

    def release_admission(self) -> None:
        with self._lock:
            self._inflight -= 1

    def assign(self, rids: list[str], w: Worker) -> None:
        with self._lock:
            for rid in rids:
                self._assigned[rid] = w.name
            w.inflight += 1
            w.requests += 1

    def unassign(self, rids: list[str], w: Worker) -> None:
        with self._lock:
            for rid in rids:
                self._assigned.pop(rid, None)
                self._claimed.discard(rid)
            w.inflight -= 1

    def assigned_worker(self, rid: str) -> Worker | None:
        """The worker currently holding ``rid`` (or any of its fan-out
        children) — the cancel-forwarding target."""
        prefix = rid + "#"
        with self._lock:
            name = self._assigned.get(rid)
            if name is None:
                for r, wn in self._assigned.items():
                    if r.startswith(prefix):
                        name = wn
                        break
            if name is None:
                return None
            for w in self.workers:
                if w.name == name:
                    return w
        return None

    # -- rolling deploy ----------------------------------------------------

    def rolling_restart(self, drain_timeout_s: float = 30.0,
                        ready_timeout_s: float = 60.0) -> dict:
        """Drain-one-restart-one behind the front door: for each spawned
        worker — out of rotation, wait for ITS router-side in-flight to
        hit zero, SIGTERM (drain + seal + exit 0), restart, back in
        rotation only once the probe loop marks it up. Runs on the
        caller's thread (the HTTP surface spawns one)."""
        with self._lock:
            if self._rolling or self._draining:
                return {"status": "already_rolling_or_draining"}
            self._rolling = True
        restarted, skipped = [], []
        try:
            for w in self.workers:
                if w.handle is None:
                    skipped.append(w.name)
                    continue
                with self._lock:
                    w.draining = True
                t_end = time.monotonic() + drain_timeout_s
                while time.monotonic() < t_end:
                    with self._lock:
                        busy = w.inflight
                    if busy == 0:
                        break
                    time.sleep(0.02)
                rc = w.handle.drain(drain_timeout_s)
                with self._lock:
                    w.up = False
                    w.ok_streak = 0
                    w.fail_streak = 0
                    w.restarts += 1
                    w.last_restart = time.monotonic()
                    w.handed_off = True  # sealed drain owes no handoff
                w.handle.start()
                t_end = time.monotonic() + ready_timeout_s
                while time.monotonic() < t_end:
                    with self._lock:
                        back = w.up
                    if back:
                        break
                    time.sleep(self.probe_interval_s / 2)
                with self._lock:
                    w.draining = False
                    w.handed_off = False
                restarted.append({"name": w.name, "drain_rc": rc})
                logger.info("rolling restart: %s drained (rc=%s) and "
                            "rejoined", w.name, rc)
        finally:
            with self._lock:
                self._rolling = False
        return {"status": "done", "restarted": restarted,
                "skipped": skipped}

    # -- introspection -----------------------------------------------------

    def health_payload(self) -> dict:
        from .. import __version__

        with self._lock:
            rows = [w.row() for w in self.workers]
            payload = {
                "status": "ok",
                "role": "router",
                "version": __version__,
                "started_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.started_wall)
                ),
                "uptime_s": round(
                    time.monotonic() - self.started_monotonic, 3
                ),
                "workers": rows,
                "workers_up": sum(1 for r in rows if r["up"]),
                "inflight": self._inflight,
                "draining": self._draining,
                "rolling": self._rolling,
                "sheds": dict(self._sheds),
                "tenant_requests": dict(self._tenant_requests),
            }
        if self.journal is not None:
            payload["journal"] = self.journal.stats_dict()
        # per-worker operator summary (outside the router lock — the
        # federation sample table carries its own leaf lock): the at-a-
        # glance block an operator reads before anything else. Fields come
        # from the worker's own snapshot when federation has one; the
        # probe-loop view covers the rest
        fed = self.federation
        for r in payload["workers"]:
            s = fed.sample(r["name"]) if fed is not None else None
            p = s.payload if s is not None else None
            wd = (p.get("watchdog") or {}) if p else {}
            r["summary"] = {
                "ready": bool(p.get("ready")) if p else r["up"],
                "readyz": p.get("readyz_reason") if p else r["reason"],
                "rung": p.get("degraded_rung", 0) if p else None,
                "inflight": r["inflight"],
                "watchdog_max_heartbeat_age_s": wd.get(
                    "max_heartbeat_age_s"
                ),
                "last_markdown_reason": r["last_markdown_reason"],
                "sample_age_s": (round(s.age_s(), 3)
                                 if s is not None else None),
            }
        if fed is not None:
            payload["federation"] = fed.stats_dict()
        payload["incidents"] = self.incidents.counts_snapshot()
        if not payload["workers_up"]:
            payload["status"] = "degraded"
        return payload

    def render_metrics(self) -> str:
        """The router's /metrics: vnsum_serve_router_* from the SAME
        registry the worker metrics use (one doc-lint surface), plus the
        vnsum_serve_journal_* gauges for the global ledger — so fleet
        soaks scrape `journal_pending` off the router exactly like the
        single-process soaks scrape the server."""
        with self._lock:
            rows = [w.row() for w in self.workers]
            sheds = dict(self._sheds)
        reg = self.worker_labels
        lines: list[str] = []

        def meta(name: str) -> None:
            typ, help_ = _METRICS[name]  # KeyError = unregistered metric
            lines.append(f"# HELP {_PREFIX}{name} {help_}")
            lines.append(f"# TYPE {_PREFIX}{name} {typ}")

        def simple(name: str, value) -> None:
            meta(name)
            lines.append(f"{_PREFIX}{name} {value}")

        simple("router_workers", len(rows))
        simple("router_workers_up", sum(1 for r in rows if r["up"]))
        for metric, key in (
            ("router_requests_total", "requests"),
            ("router_failovers_total", "failovers"),
            ("router_markdowns_total", "markdowns"),
            ("router_markups_total", "markups"),
            ("router_restarts_total", "restarts"),
            ("router_probe_seconds", "probe_s"),
        ):
            meta(metric)
            for r in rows:
                name = r["name"]
                # worker= values pass through the bounded roster registry:
                # canonical() collapses anything off-roster into "other",
                # which is what the metric-label-cardinality rule checks
                lines.append(
                    f'{_PREFIX}{metric}'
                    f'{{worker="{reg.canonical(name, touch=False)}"}}'
                    f" {r[key]}"
                )
        meta("router_sheds_total")
        for reason in _SHED_REASONS:
            lines.append(
                # lint-allow[metric-label-cardinality]: reason iterates the _SHED_REASONS module constant — four literal front-door shed classes, nothing request-derived
                f'{_PREFIX}router_sheds_total{{reason="{reason}"}} '
                f"{sheds.get(reason, 0)}"
            )
        if self.journal is not None:
            js = self.journal.stats_dict()
            simple("journal_records_total", js.get("records", 0))
            simple("journal_appended_bytes_total",
                   js.get("appended_bytes", 0))
            simple("journal_fsyncs_total", js.get("fsyncs", 0))
            simple("journal_rotations_total", js.get("rotations", 0))
            simple("journal_torn_records_total", js.get("torn_records", 0))
            simple("journal_replayed_total", js.get("replayed", 0))
            simple("journal_replay_seconds_total",
                   js.get("replay_seconds", 0.0))
            simple("journal_pending", js.get("pending", 0))
        # fleet federation rollups + per-worker gauges (the scrape loop's
        # re-export) and the incident counter, by typed trigger reason
        if self.federation is not None:
            lines.extend(self.federation.metrics_lines(reg))
        inc = self.incidents.counts_snapshot()
        meta("fleet_incidents_total")
        for reason in ("slo_fast_burn", "markdown", "failover",
                       "operator"):
            lines.append(
                f'{_PREFIX}fleet_incidents_total{{reason="{reason}"}} '
                f"{inc.get(reason, 0)}"
            )
        return "\n".join(lines) + "\n"


# -- HTTP surface -------------------------------------------------------------


def make_router_handler(state: RouterState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        MAX_BODY_BYTES = 16 * 1024 * 1024

        _rid: str | None = None
        _trace_status: str = "ok"

        # -- plumbing (same response contract as serve/server.py) ---------

        def _json(self, payload: dict, status: int = 200,
                  headers: dict | None = None) -> None:
            if self._rid is not None:
                payload = {"request_id": self._rid, **payload}
            body = json.dumps(payload, ensure_ascii=False).encode()
            self.send_response(status)
            self.send_header("Content-Type",
                             "application/json; charset=utf-8")
            if self._rid is not None:
                self.send_header("X-Request-Id", self._rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _shed(self, reason: str, status: int,
                  retry_after_s: float = 1.0) -> None:
            state.shed(reason)
            self._json(
                {"error": "shed", "reason": reason,
                 "retry_after_s": retry_after_s},
                status,
                {"Retry-After": str(max(1, int(round(retry_after_s))))},
            )

        def _read_json(self) -> dict | None:
            try:
                length = int(self.headers.get("Content-Length", "0"))
            # lint-allow[swallowed-exception]: a garbled header becomes length=-1, answered with a typed 400 below
            except ValueError:
                length = -1
            if length < 0 or length > self.MAX_BODY_BYTES:
                self.close_connection = True
                if length < 0:
                    self._json({"error": "bad Content-Length"}, 400)
                else:
                    self._json({"error": "request body too large"}, 413)
                return None
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                self._json({"error": "invalid JSON"}, 400)
                return None
            except UnicodeDecodeError:
                self._json({"error": "request body is not valid UTF-8"},
                           400)
                return None
            if not isinstance(req, dict):
                self._json({"error": "malformed request"}, 400)
                return None
            return req

        def _tenant(self) -> tuple[str, str] | None:
            """(tenant, tier) against the router's table; unknown names
            are a typed 400 like the worker's — the front door owns
            admission, so it owns the rejection too."""
            name = self.headers.get("X-Tenant")
            if state.tenants is None or name is None:
                return (name or "", "interactive")
            if name not in state.tenants:
                self._json(
                    {"error": f"unknown tenant {name!r}",
                     "tenants": sorted(state.tenants)}, 400,
                )
                return None
            return name, state.tenants[name]

        # -- verbs --------------------------------------------------------

        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            self._rid = None
            path, _, _query = self.path.partition("?")
            if path == "/healthz":
                self._json(state.health_payload())
            elif path == "/readyz":
                ready, reason = state.readiness()
                if ready:
                    self._json({"status": "ready", "role": "router"})
                else:
                    self._json(
                        {"error": "not_ready", "reason": reason,
                         "retry_after_s": 1.0},
                        503, {"Retry-After": "1"},
                    )
            elif path == "/metrics":
                body = state.render_metrics().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; "
                    "charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/debug/trace":
                self._debug_trace()
            elif path == "/debug/slo":
                if state.federation is None:
                    self._json({"error": "federation disabled "
                                         "(--no-federation)"}, 404)
                else:
                    self._json(state.federation.fleet_slo())
            elif path == "/v1/usage":
                if state.federation is None:
                    self._json({"error": "federation disabled "
                                         "(--no-federation)"}, 404)
                else:
                    self._json(state.federation.fleet_usage())
            elif path == "/debug/flightrecorder":
                # the routing-decision ring: the router's half of every
                # incident bundle, readable without minting one
                self._json(state.recorder.snapshot())
            elif path.startswith("/v1/requests/"):
                self._request_status(path[len("/v1/requests/"):])
            else:
                self._json({"error": f"unknown path {path}"}, 404)

        def _debug_trace(self) -> None:
            """ONE merged Chrome trace for the whole fleet: a fresh
            federation sweep pulls every worker's span ring (and its
            clock offset from the scrape RTT midpoint), the router's own
            proxy spans join as the reference-clock group, and traces
            sharing an id — including the pre-/post-failover halves of a
            handed-off request — land in one Perfetto process."""
            from ..obs.export import merged_chrome_trace, trace_state_payload

            groups = [{
                "source": "router",
                "clock_offset_s": 0.0,
                "traces": trace_state_payload(state.obs.snapshot()[0]),
            }]
            if state.federation is not None:
                state.federation.scrape_all()
                groups.extend(state.federation.trace_groups())
            self._json(merged_chrome_trace(groups))

        def do_POST(self) -> None:  # noqa: N802 (stdlib API)
            self._rid = None
            path, _, _query = self.path.partition("?")
            if path in ("/v1/generate", "/v1/summarize"):
                self._proxy(path)
            elif path == "/admin/rolling-restart":
                threading.Thread(
                    target=state.rolling_restart,
                    name="rolling-restart", daemon=True,
                ).start()
                self._json({"status": "rolling"}, 202)
            else:
                self._json({"error": f"unknown path {path}"}, 404)

        def do_DELETE(self) -> None:  # noqa: N802 (stdlib API)
            self._rid = None
            path, _, _query = self.path.partition("?")
            if not path.startswith("/v1/requests/"):
                self._json({"error": f"unknown path {path}"}, 404)
                return
            self._cancel(path[len("/v1/requests/"):])

        # -- the proxy hot path -------------------------------------------

        def _proxy(self, path: str) -> None:
            req = self._read_json()
            if req is None:
                return
            try:
                self._rid = _request_id(req, self.headers)
            except _BadRequest as e:
                self._json({"error": str(e)}, 400)
                return
            qos = self._tenant()
            if qos is None:
                return
            tenant, tier = qos
            if req.get("stream"):
                # SSE pass-through needs chunked relay plumbing the thin
                # front door doesn't have yet; a streaming client can
                # speak to a worker directly
                state.shed("stream_unsupported")
                self._json(
                    {"error": "stream_unsupported",
                     "detail": "the fleet router does not proxy SSE; "
                               "POST without stream or address a worker "
                               "directly"}, 501,
                )
                return
            shed_reason = state.admit(tenant)
            if shed_reason is not None:
                self._shed(shed_reason,
                           503 if shed_reason == "shutdown" else 429)
                return
            # root of the stitched fleet trace: the router's own span ring
            # records the proxy hop(s); workers nest under it via the
            # X-Parent-Span header the dispatch forwards
            trace = (state.obs.start_request(self._rid)
                     if state.obs is not None else None)
            self._trace_status = "ok"
            try:
                self._dispatch(path, req, tenant, tier, trace)
            finally:
                state.release_admission()
                if state.obs is not None:
                    state.obs.finish_request(trace, self._trace_status)

        def _journal_accepts(self, path: str, req: dict, tenant: str,
                             tier: str) -> list[str]:
            """ACCEPT every prompt of this request into the GLOBAL ledger
            before any dispatch — the handoff/replay source. Fan-out
            children get ``rid#N`` names in prompt order, matching the
            worker-side naming so the two ledgers correlate."""
            if state.journal is None:
                return []
            try:
                max_new_tokens = _number(req, "max_new_tokens", int,
                                         integer=True)
                config = _gen_config_from(req)
                deadline = _deadline_from(req, state.default_deadline_s)
            except _BadRequest:
                # the worker owns field validation and will answer the
                # typed 400 — nothing journaled for a rejected body
                return []
            if path == "/v1/summarize":
                reqs = [_RouterRequest(
                    trace_id=self._rid, prompt=req.get("text", ""),
                    max_new_tokens=max_new_tokens, deadline=deadline,
                    tenant=tenant, tier=tier,
                    approach=req.get("approach", "mapreduce"),
                )]
            else:
                prompts = req.get("prompts")
                if not isinstance(prompts, list):
                    prompts = [req.get("prompt", "")]
                refs = req.get("references")
                if not isinstance(refs, list):
                    refs = [req.get("reference")] * len(prompts)
                hints = req.get("cache_hints")
                if not isinstance(hints, list):
                    hints = [req.get("cache_hint")] * len(prompts)
                reqs = [
                    _RouterRequest(
                        trace_id=self._rid, prompt=p,
                        max_new_tokens=max_new_tokens, config=config,
                        reference=refs[i] if i < len(refs) else None,
                        cache_hint=hints[i] if i < len(hints) else None,
                        deadline=deadline, tenant=tenant, tier=tier,
                    )
                    for i, p in enumerate(prompts)
                ]
            return [state.journal.accept(r) for r in reqs]

        def _dispatch(self, path: str, req: dict, tenant: str,
                      tier: str, trace=None) -> None:
            t_acc = time.monotonic()
            rids = self._journal_accepts(path, req, tenant, tier)
            if trace is not None:
                trace.add("journal_accept", t_acc,
                          time.monotonic() - t_acc, rids=len(rids))
            affinity = (
                req.get("cache_hint")
                or next((h for h in (req.get("cache_hints") or [])
                         if h), None)
                or tenant or None
            )
            body = {**req, "request_id": self._rid}
            fwd_headers = {"X-Request-Id": self._rid,
                           "X-Parent-Span": f"router:{self._rid}"}
            if tenant:
                fwd_headers["X-Tenant"] = tenant
            tried: set[str] = set()
            claimed_by_me = False
            attempts = max(2, len(state.workers) + 1)
            for _attempt in range(attempts):
                w = state.pick(affinity, exclude=tried)
                if w is None and _attempt + 1 < attempts:
                    # a kill/mark-down window can leave zero routable
                    # workers for a probe beat; wait one out (and forget
                    # exclusions — a marked-up worker is fair game again)
                    # before shedding the client
                    tried.clear()
                    time.sleep(min(0.25, state.probe_interval_s * 2))
                    continue
                if w is None:
                    for rid in rids:
                        state.journal.fail(rid, "shed:no_worker",
                                           "no routable worker")
                        state._release(rid)
                    self._trace_status = "shed"
                    self._shed("no_worker", 503)
                    return
                state.assign(rids, w)
                state.recorder.record("route", rid=self._rid,
                                      worker=w.name, path=path)
                for rid in rids:
                    state.journal.start(rid) if state.journal else None
                t_req = time.monotonic()
                try:
                    status, resp = state._worker_http(
                        w, "POST", path, body=body, headers=fwd_headers,
                        timeout=state.proxy_timeout_s,
                    )
                except OSError as e:
                    # inline failover: the client is still on the line —
                    # claim the rids (so the probe-loop handoff skips
                    # them) and re-dispatch onto a survivor ourselves. The
                    # claim is checked ONCE: on a later hop (a second
                    # worker dying under the same request) we already own
                    # the claim and must keep retrying, not mistake our
                    # own claim for a concurrent handoff and orphan the
                    # rids non-terminal
                    if trace is not None:
                        # the PRE-failover half: this span and the
                        # re-dispatch onto a survivor share one trace id,
                        # which is what joins them in the merged trace
                        trace.add("proxy", t_req,
                                  time.monotonic() - t_req,
                                  worker=w.name, outcome="failover")
                    already = False
                    with state._lock:
                        w.inflight -= 1
                        w.fail_streak += 1
                        w.ok_streak = 0
                        if not claimed_by_me:
                            if any(r in state._claimed for r in rids):
                                already = True
                            else:
                                state._claimed.update(rids)
                                claimed_by_me = True
                        if not already:
                            w.failovers += len(rids) or 1
                    state.recorder.record("failover", rid=self._rid,
                                          worker=w.name,
                                          error=str(e)[:120])
                    state.incidents.trigger(
                        "failover", detail=f"{w.name}: {e}"
                    )
                    if already:
                        # a probe-loop handoff owns these rids; the result
                        # lands in the ledger — point the client at it
                        self._trace_status = "failover_in_progress"
                        self._json(
                            {"error": "failover_in_progress",
                             "detail": f"poll /v1/requests/{self._rid}"},
                            503, {"Retry-After": "1"},
                        )
                        return
                    tried.add(w.name)
                    logger.warning("proxy to %s failed (%s) — inline "
                                   "failover", w.name, e)
                    continue
                if trace is not None:
                    trace.add("proxy", t_req, time.monotonic() - t_req,
                              worker=w.name, status=status)
                if status != 200:
                    self._trace_status = f"http_{status}"
                self._settle(path, rids, w, status, resp)
                return
            for rid in rids:
                state.journal.fail(rid, "failover:exhausted",
                                   "inline retries exhausted")
                state._release(rid)
            self._trace_status = "failover_exhausted"
            self._shed("no_worker", 503)

        def _settle(self, path: str, rids: list[str], w: Worker,
                    status: int, resp: dict | None) -> None:
            """Fold the worker's answer into the global ledger, then relay
            it verbatim — the client sees exactly what the worker said
            (plus the router's X-Request-Id echo)."""
            state.unassign(rids, w)
            if state.journal is not None:
                if status == 200:
                    if path == "/v1/summarize":
                        state._journal_success(rids[0], path, resp)
                    else:
                        comps = (resp or {}).get("completions") or []
                        for i, rid in enumerate(rids):
                            c = comps[i] if i < len(comps) else {}
                            state.journal.complete(
                                rid, c.get("text", ""),
                                (c.get("record") or {}).get(
                                    "generated_tokens", 0
                                ),
                            )
                else:
                    reason = (
                        f"shed:{(resp or {}).get('reason', status)}"
                        if status in (429, 503)
                        else f"http:{status}"
                    )
                    detail = json.dumps(resp)[:200] if resp else ""
                    for rid in rids:
                        state.journal.fail(rid, reason, detail)
            headers = {}
            if isinstance(resp, dict) and "retry_after_s" in resp:
                headers["Retry-After"] = str(
                    max(1, int(round(resp["retry_after_s"])))
                )
            self._json(resp if isinstance(resp, dict) else
                       {"error": f"worker answered {status}"},
                       status, headers)

        # -- poll + cancel ------------------------------------------------

        def _request_status(self, raw_rid: str) -> None:
            import urllib.parse

            rid = urllib.parse.unquote(raw_rid)
            if state.journal is None:
                self._json(
                    {"error": "journaling disabled (--journal-dir unset)"},
                    404,
                )
                return
            entries = state.journal.lookup(rid)
            if not entries:
                self._json(
                    {"error": f"unknown or expired request id {rid!r}"},
                    404,
                )
                return
            self._json({
                "request_id": rid,
                "status": aggregate_status(entries),
                "entries": [e.to_dict() for e in entries],
            })

        def _cancel(self, raw_rid: str) -> None:
            import urllib.parse

            rid = urllib.parse.unquote(raw_rid)
            self._rid = rid
            w = state.assigned_worker(rid)
            if w is not None:
                try:
                    status, resp = state._worker_http(
                        w, "DELETE", f"/v1/requests/{raw_rid}",
                        timeout=30.0,
                    )
                # lint-allow[swallowed-exception]: status=None routes to the ledger-side cancel fallback below, which always answers the client
                except OSError:
                    # the worker died under the cancel: the ledger closes
                    # the entries directly (idempotent against a handoff
                    # completing them first)
                    status, resp = None, None
                if status is not None:
                    if state.journal is not None:
                        for e in state.journal.lookup(rid):
                            if not e.terminal:
                                state.journal.cancel(e.rid, "api")
                    self._json(resp if isinstance(resp, dict) else
                               {"status": "cancelled"}, status)
                    return
            if state.journal is None:
                self._json(
                    {"error": "journaling disabled (--journal-dir unset)"},
                    404,
                )
                return
            entries = state.journal.lookup(rid)
            if not entries:
                self._json(
                    {"error": f"unknown or expired request id {rid!r}"},
                    404,
                )
                return
            cancelled = 0
            for e in entries:
                if not e.terminal:
                    state.journal.cancel(e.rid, "api")
                    cancelled += 1
            entries = state.journal.lookup(rid)
            self._json({
                "request_id": rid,
                "cancelled_queued": cancelled,
                "cancel_pending": False,
                "status": aggregate_status(entries),
            })

        def log_message(self, fmt: str, *args) -> None:
            logger.info("%s %s", self.address_string(), fmt % args)

    return Handler


class _RouterServer(ThreadingHTTPServer):
    # same rationale as serve/server.py's _Server: the kernel should queue
    # connect bursts, not clients retransmitting SYNs
    request_queue_size = 128
    daemon_threads = True


def make_router_server(
    state: RouterState, host: str = "127.0.0.1", port: int = 8900
) -> ThreadingHTTPServer:
    return _RouterServer((host, port), make_router_handler(state))


# -- CLI ----------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="vnsum-serve-router")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8900)
    p.add_argument("--workers", default=None,
                   help="comma-separated host:port endpoints of externally "
                        "managed workers (mutually exclusive with "
                        "--spawn-workers)")
    p.add_argument("--spawn-workers", type=int, default=0,
                   help="spawn N engine workers as subprocesses under "
                        "--fleet-dir (the router owns their lifecycle: "
                        "crash respawn + rolling restarts)")
    p.add_argument("--fleet-dir", default=None,
                   help="fleet state directory: per-worker journal subdirs "
                        "plus the router's own journal at <fleet>/router")
    p.add_argument("--backend", default="fake",
                   help="backend flag forwarded to spawned workers")
    p.add_argument("--worker-args", default="",
                   help="extra flags forwarded verbatim to every spawned "
                        "worker (shlex-split)")
    p.add_argument("--journal-dir", default=None,
                   help="router journal directory (default: "
                        "<fleet-dir>/router when --fleet-dir is set)")
    p.add_argument("--journal-fsync-ms", type=float, default=50.0)
    p.add_argument("--probe-interval-ms", type=float, default=250.0)
    p.add_argument("--probe-timeout-ms", type=float, default=2000.0)
    p.add_argument("--down-after", type=int, default=2,
                   help="consecutive probe failures before mark-down")
    p.add_argument("--up-after", type=int, default=1,
                   help="consecutive probe successes before mark-up")
    p.add_argument("--max-inflight", type=int, default=256,
                   help="global front-door admission cap (typed 429 past "
                        "it)")
    p.add_argument("--proxy-timeout-s", type=float, default=120.0)
    p.add_argument("--default-deadline-ms", type=float, default=0.0)
    p.add_argument("--tenants", default=None,
                   help="QoS table (name:weight:token_rate[:tier],...): "
                        "validated at the front door and forwarded to "
                        "spawned workers")
    p.add_argument("--no-restart-crashed", action="store_true",
                   help="do not respawn crashed spawned workers (handoff "
                        "still replays their unfinished work)")
    p.add_argument("--no-probe-slo-burn", action="store_true",
                   help="ignore worker SLO burn verdicts in the mark-down "
                        "hysteresis")
    p.add_argument("--federation-interval-ms", type=float, default=1000.0,
                   help="fleet federation scrape cadence (worker "
                        "/debug/obs/snapshot JSON); rollups re-export on "
                        "the router /metrics as vnsum_serve_fleet_*")
    p.add_argument("--no-federation", action="store_true",
                   help="disable the federation scrape loop: no fleet "
                        "rollups, fleet /debug/slo and /v1/usage answer "
                        "404, /debug/trace carries router spans only")
    p.add_argument("--incident-dir", default=None,
                   help="incident bundle directory (default: "
                        "<fleet-dir>/incidents when --fleet-dir is set); "
                        "unset without --fleet-dir = incident capture off")
    p.add_argument("--incident-min-interval-s", type=float, default=30.0,
                   help="per-trigger-reason incident capture throttle")
    p.add_argument("--drain-timeout-s", type=float, default=30.0)
    args = p.parse_args(argv)

    if bool(args.workers) == bool(args.spawn_workers):
        p.error("exactly one of --workers / --spawn-workers is required")
    if args.spawn_workers and not args.fleet_dir:
        p.error("--spawn-workers requires --fleet-dir")

    tenants = None
    if args.tenants:
        from .qos import parse_tenant_specs

        tenants = {name: spec.tier
                   for name, spec in parse_tenant_specs(args.tenants).items()}

    workers: list[Worker] = []
    if args.spawn_workers:
        from .worker import build_fleet

        fleet_dir = Path(args.fleet_dir)
        fleet_dir.mkdir(parents=True, exist_ok=True)
        worker_args = ["--backend", args.backend,
                       *shlex.split(args.worker_args)]
        if args.tenants:
            worker_args += ["--tenants", args.tenants]
        for h in build_fleet(args.spawn_workers, str(fleet_dir),
                             extra_args=worker_args):
            h.start()
            workers.append(Worker(h.name, h.host, h.port, handle=h))
        if args.journal_dir is None:
            args.journal_dir = str(fleet_dir / "router")
        if args.incident_dir is None:
            args.incident_dir = str(fleet_dir / "incidents")
    else:
        for i, ep in enumerate(
            s.strip() for s in args.workers.split(",") if s.strip()
        ):
            host, _, port = ep.rpartition(":")
            workers.append(Worker(f"worker-{i}", host or "127.0.0.1",
                                  int(port)))

    state = RouterState(
        workers,
        journal_dir=args.journal_dir,
        journal_fsync_s=args.journal_fsync_ms / 1000.0,
        probe_interval_s=args.probe_interval_ms / 1000.0,
        probe_timeout_s=args.probe_timeout_ms / 1000.0,
        down_after=args.down_after,
        up_after=args.up_after,
        max_inflight=args.max_inflight,
        proxy_timeout_s=args.proxy_timeout_s,
        default_deadline_s=(
            args.default_deadline_ms / 1000.0
            if args.default_deadline_ms else None
        ),
        tenants=tenants,
        restart_crashed=not args.no_restart_crashed,
        probe_slo_burn=not args.no_probe_slo_burn,
        federate=not args.no_federation,
        federation_interval_s=args.federation_interval_ms / 1000.0,
        incident_dir=args.incident_dir,
        incident_min_interval_s=args.incident_min_interval_s,
    )
    state.start()
    server = make_router_server(state, args.host, args.port)
    logger.info("router listening on %s:%d over %d worker(s)%s",
                args.host, args.port, len(workers),
                " (spawned)" if args.spawn_workers else "")

    def _graceful(signum, frame) -> None:
        logger.info("signal %d: shutting down router", signum)
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    def _operator_incident(signum, frame) -> None:
        # operator-triggered correlated capture: mint an incident and fan
        # the dump out off the signal frame (capture does worker HTTP)
        threading.Thread(
            target=state.incidents.trigger,
            kwargs={"reason": "operator", "detail": "SIGUSR1",
                    "sync": True},
            name="operator-incident", daemon=True,
        ).start()

    if hasattr(signal, "SIGUSR1"):
        signal.signal(signal.SIGUSR1, _operator_incident)
    try:
        server.serve_forever()
    finally:
        state.close(args.drain_timeout_s)
        server.server_close()
    logger.info("router shut down cleanly")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
