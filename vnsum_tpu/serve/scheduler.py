"""Micro-batching scheduler: many concurrent requests, one engine thread.

The engine already solves the hard problem — a list of prompts becomes
bucketed, fixed-shape device batches (backend/engine.py) — but it is an
offline API: someone must hand it the list. This scheduler is that someone
for online traffic. Requests arrive on arbitrary threads (HTTP handlers,
strategy rounds), sit in the bounded RequestQueue, and ONE scheduler thread
coalesces compatible requests (same max_new_tokens + GenerationConfig) into
shared backend.generate calls under a max-wait/max-batch policy:

- heavy load: batches fill to ``max_batch`` immediately — throughput-optimal,
  the engine's bucketing amortizes prefill+decode across the batch;
- light load: a lone request waits at most ``max_wait_s`` before dispatching
  alone — latency stays bounded instead of waiting for company that never
  comes (the standard micro-batching latency/throughput dial, BASS
  arXiv:2404.15778 §3).

Single-threaded engine access is load-bearing, not incidental: TpuBackend's
jit caches, stats, and dispatch counter are not thread-safe, and the demo
server previously serialized whole summarize requests behind a lock to cope.
Here serialization happens per engine BATCH, after coalescing — the lock
contention becomes the batching opportunity.

QueuedBackend closes the loop for the strategy layer: it implements the
Backend protocol by submitting each prompt of a strategy round as its own
queued request and waiting on the futures. Concurrent strategy runs (e.g.
two /v1/summarize requests in flight) therefore interleave their map/collapse
rounds into shared engine batches — re-entrant batch submission without the
strategies knowing the serving layer exists.
"""
from __future__ import annotations

import threading
import time

from ..backend.base import Backend
from ..core.config import GenerationConfig
from ..core.logging import get_logger
from ..core.results import ServeRequestRecord
from .metrics import ServeMetrics
from .queue import RequestQueue, RequestShed, ServeRequest, ShedReason

logger = get_logger("vnsum.serve")


class _Completion:
    """What a request future resolves to: the text plus its observability
    record (the HTTP layer returns the record inline with the response)."""

    __slots__ = ("text", "record")

    def __init__(self, text: str, record: ServeRequestRecord) -> None:
        self.text = text
        self.record = record


class MicroBatchScheduler:
    def __init__(
        self,
        backend: Backend,
        *,
        max_batch: int = 8,
        max_wait_s: float = 0.01,
        max_queue_depth: int = 256,
        max_queued_tokens: int = 0,
        metrics: ServeMetrics | None = None,
    ) -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.backend = backend
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.metrics = metrics or ServeMetrics()
        self.queue = RequestQueue(
            max_depth=max_queue_depth, max_queued_tokens=max_queued_tokens
        )
        self.queue.on_shed = self._on_shed
        self.queue.on_admit = lambda req: self.metrics.observe_submit()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="vnsum-serve-scheduler", daemon=True
        )
        self._thread.start()

    # -- submission ------------------------------------------------------

    def submit(
        self,
        prompt: str,
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        deadline: float | None = None,
        internal: bool = False,
        reference: str | None = None,
    ):
        """Admit one prompt; returns a Future resolving to a _Completion.
        Raises RequestShed synchronously when admission control rejects.
        ``internal=True`` marks fan-out of already-admitted work (strategy
        rounds riding a QueuedBackend): depth/token admission is skipped —
        the request-level gate is check_admission — while deadline and
        shutdown shedding still apply. ``reference`` rides the request as
        per-row speculation metadata (never part of the batch key)."""
        req = ServeRequest(
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            config=config,
            reference=reference,
            deadline=deadline,
            est_tokens=self.backend.count_tokens(prompt),
        )
        # the admit is counted by the queue's on_admit hook, under the queue
        # lock, so metrics can never show a completion before its submit
        return self.queue.submit(req, force=internal)  # raises RequestShed

    def check_admission(self, est_tokens: int = 0) -> None:
        """Request-level admission gate for entry points that fan out via
        internal submits; sheds are counted in metrics like any other."""
        try:
            self.queue.check_admission(est_tokens)
        except RequestShed as e:
            self.metrics.observe_shed(e.reason)
            raise

    def submit_many(self, prompts, references=None, **kw):
        """Admit a round of prompts atomically-ish: if any prompt is shed at
        admission, already-admitted siblings are left to complete (they
        occupy queue slots either way) and the shed propagates to the
        caller — a strategy round is all-or-nothing for its caller.
        ``references`` optionally aligns one speculation reference per
        prompt."""
        if references is None:
            references = [None] * len(prompts)
        return [
            self.submit(p, reference=r, **kw)
            for p, r in zip(prompts, references)
        ]

    def generate_sync(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        deadline: float | None = None,
        internal: bool = False,
        references: list[str | None] | None = None,
    ) -> list[_Completion]:
        futs = self.submit_many(
            prompts, references=references, max_new_tokens=max_new_tokens,
            config=config, deadline=deadline, internal=internal,
        )
        return [f.result() for f in futs]

    def backend_view(self, deadline: float | None = None) -> "QueuedBackend":
        """A Backend-protocol view whose generate() routes through this
        scheduler — hand it to a strategy to make its rounds coalesce with
        everyone else's."""
        return QueuedBackend(self, deadline=deadline)

    # -- scheduler thread ------------------------------------------------

    def _on_shed(self, req: ServeRequest, reason: ShedReason) -> None:
        self.metrics.observe_shed(reason)

    def _loop(self) -> None:
        while True:
            try:
                batch = self.queue.take_batch(self.max_batch, self.max_wait_s)
            except Exception:  # pragma: no cover - queue bugs must not kill serving
                logger.exception("take_batch failed; scheduler continuing")
                continue
            if batch is None:
                return  # closed and drained
            try:
                self._run_batch(batch)
            except Exception as e:  # pragma: no cover - belt and braces
                # _run_batch guards backend.generate, but anything raising
                # after it (token counting, metrics) must not kill the
                # scheduler thread: callers block on these futures forever
                # and /healthz would keep reporting ok
                logger.exception("batch post-processing failed")
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)

    def _run_batch(self, batch: list[ServeRequest]) -> None:
        head = batch[0]
        t0 = time.monotonic()
        try:
            outs = self.backend.generate(
                [r.prompt for r in batch],
                max_new_tokens=head.max_new_tokens,
                config=head.config,
                references=[r.reference for r in batch],
            )
        except Exception as e:
            engine_s = time.monotonic() - t0
            self.metrics.observe_batch(len(batch), engine_s)
            logger.exception("engine batch of %d failed", len(batch))
            for r in batch:
                rec = self._record(r, "error", t0, engine_s, len(batch), 0)
                self.metrics.observe_request(rec)
                if not r.future.done():
                    r.future.set_exception(e)
            return
        engine_s = time.monotonic() - t0
        self.metrics.observe_batch(len(batch), engine_s)
        if len(outs) != len(batch):
            # a zip would silently drop the tail and strand its futures
            e = RuntimeError(
                f"backend returned {len(outs)} outputs for a batch of "
                f"{len(batch)}"
            )
            logger.error(str(e))
            for r in batch:
                rec = self._record(r, "error", t0, engine_s, len(batch), 0)
                self.metrics.observe_request(rec)
                if not r.future.done():
                    r.future.set_exception(e)
            return
        gen_tokens = self.backend.count_tokens_batch(outs)
        # per-request speculative-decoding attribution: backends with the
        # spec path expose take_spec_report() — per-prompt records aligned
        # with the batch, cleared on read. Engine access is single-threaded
        # (this scheduler thread), so read-after-generate cannot race.
        take_spec = getattr(self.backend, "take_spec_report", None)
        spec_report = take_spec() if callable(take_spec) else []
        if len(spec_report) != len(batch):
            spec_report = [None] * len(batch)
        for r, out, n_out, spec in zip(batch, outs, gen_tokens, spec_report):
            rec = self._record(r, "ok", t0, engine_s, len(batch), n_out)
            if spec is not None:
                rec.draft_tokens = spec.draft_tokens
                rec.accepted_tokens = spec.accepted_tokens
            self.metrics.observe_request(rec)
            if not r.future.done():
                r.future.set_result(_Completion(out, rec))

    def _record(self, r, status, t0, engine_s, batch_size, gen_tokens):
        now = time.monotonic()
        return ServeRequestRecord(
            request_id=r.request_id,
            status=status,
            queue_wait_s=max(t0 - r.enqueued_at, 0.0),
            engine_s=engine_s,
            total_s=max(now - r.enqueued_at, 0.0),
            batch_size=batch_size,
            prompt_tokens=r.est_tokens,
            generated_tokens=gen_tokens,
        )

    # -- lifecycle -------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admitting; drain=True runs remaining queued batches to
        completion before the scheduler thread exits."""
        self._closed = True
        self.queue.close(drain=drain)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():  # pragma: no cover - drain overrun
            logger.warning("scheduler did not drain within %.1fs", timeout)

    @property
    def closed(self) -> bool:
        return self._closed


class QueuedBackend:
    """Backend-protocol adapter over a MicroBatchScheduler.

    generate() fans each prompt into its own queued request and blocks until
    every future resolves, so a strategy's per-round batched call becomes N
    coalescible units — two strategies running concurrently share engine
    batches instead of serializing whole runs. Token counting delegates
    straight to the real backend (host-side, thread-safe, no queue trip).

    A RequestShed on any prompt of a round propagates to the caller: the
    strategy run is aborted with the typed shed, matching the all-or-nothing
    semantics a deadline implies. ``records`` accumulates the per-request
    observability of every completed prompt for response-inline reporting.
    """

    name = "queued"

    def __init__(self, scheduler: MicroBatchScheduler,
                 deadline: float | None = None) -> None:
        self.scheduler = scheduler
        self.deadline = deadline
        self.records: list[ServeRequestRecord] = []
        self._lock = threading.Lock()

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
    ) -> list[str]:
        if not prompts:
            return []
        # internal: this is the fan-out of an already-admitted request —
        # its admission happened at the entry point (check_admission), so a
        # wide strategy round must not shed itself against the depth budget
        completions = self.scheduler.generate_sync(
            prompts, max_new_tokens=max_new_tokens, config=config,
            deadline=self.deadline, internal=True, references=references,
        )
        with self._lock:
            self.records.extend(c.record for c in completions)
        return [c.text for c in completions]

    def count_tokens(self, text: str) -> int:
        return self.scheduler.backend.count_tokens(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return self.scheduler.backend.count_tokens_batch(texts)
