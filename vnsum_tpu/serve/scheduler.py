"""Micro-batching scheduler: many concurrent requests, one engine thread.

The engine already solves the hard problem — a list of prompts becomes
bucketed, fixed-shape device batches (backend/engine.py) — but it is an
offline API: someone must hand it the list. This scheduler is that someone
for online traffic. Requests arrive on arbitrary threads (HTTP handlers,
strategy rounds), sit in the bounded RequestQueue, and ONE scheduler thread
coalesces compatible requests (same max_new_tokens + GenerationConfig) into
shared backend.generate calls under a max-wait/max-batch policy:

- heavy load: batches fill to ``max_batch`` immediately — throughput-optimal,
  the engine's bucketing amortizes prefill+decode across the batch;
- light load: a lone request waits at most ``max_wait_s`` before dispatching
  alone — latency stays bounded instead of waiting for company that never
  comes (the standard micro-batching latency/throughput dial, BASS
  arXiv:2404.15778 §3).

Single-threaded engine access is load-bearing, not incidental: TpuBackend's
jit caches, stats, and dispatch counter are not thread-safe, and the demo
server previously serialized whole summarize requests behind a lock to cope.
Here serialization happens per engine BATCH, after coalescing — the lock
contention becomes the batching opportunity.

QueuedBackend closes the loop for the strategy layer: it implements the
Backend protocol by submitting each prompt of a strategy round as its own
queued request and waiting on the futures. Concurrent strategy runs (e.g.
two /v1/summarize requests in flight) therefore interleave their map/collapse
rounds into shared engine batches — re-entrant batch submission without the
strategies knowing the serving layer exists.

Fault tolerance (serve/supervisor.py, opt-in via ``supervisor=``; the HTTP
server opts in by default): engine dispatch failures are classified
(transient / resource-exhausted / poison / fatal), survivors retried under
bounded jittered backoff with a per-request budget, crashing batches
bisected to quarantine the poison request (typed RequestFailed on ITS
future, everyone else completes), and repeated resource failures step a
degradation ladder down (shrink batch -> no spec -> no cache inserts ->
brownout) with probed recovery. Without a supervisor the pre-supervision
contract holds: a failure resolves every rider with the raw error.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from concurrent.futures import InvalidStateError

from ..analysis.sanitizers import make_lock
from ..backend.base import Backend
from ..core.config import GenerationConfig
from ..core.logging import get_logger
from ..core.results import ServeRequestRecord
from ..obs import ObsHub, RequestTrace, reset_collector, set_collector
from .metrics import ServeMetrics
from .queue import (
    RequestCancelled,
    RequestQueue,
    RequestShed,
    ServeRequest,
    ShedReason,
)

logger = get_logger("vnsum.serve")


class _Completion:
    """What a request future resolves to: the text plus its observability
    record (the HTTP layer returns the record inline with the response)."""

    __slots__ = ("text", "record")

    def __init__(self, text: str, record: ServeRequestRecord) -> None:
        self.text = text
        self.record = record


class MicroBatchScheduler:
    def __init__(
        self,
        backend: Backend,
        *,
        max_batch: int = 8,
        max_wait_s: float = 0.01,
        max_queue_depth: int = 256,
        max_queued_tokens: int = 0,
        metrics: ServeMetrics | None = None,
        obs: ObsHub | None = None,
        trace_dir: str | None = None,
        supervisor=None,
        journal=None,
        tenants=None,
        recorder=None,
        watchdog=None,
    ) -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.backend = backend
        # drain is scoped to A server, not the backend's lifetime: a
        # backend reused across a closed-and-rebuilt scheduler (tests,
        # multi-phase benches) must simulate real sleeps/faults again
        reset_drain = getattr(backend, "reset_drain", None)
        if callable(reset_drain):
            reset_drain()
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.metrics = metrics or ServeMetrics()
        # flight recorder (obs/recorder.py): None = no black box — the
        # lifecycle paths then pay only `is None` checks (the bench A/B's
        # all-off arm). With one, every typed transition appends a
        # tuple-cheap event and anomalies (brownout entry, fatal failure,
        # quarantine, SLO fast-burn, drain) snapshot the ring to disk
        self.recorder = recorder
        # durability (serve/journal.py): None = volatile serving (the
        # pre-journal contract). With a RequestJournal, every admission
        # writes an ACCEPT record before any engine work and every outcome
        # appends COMPLETE or a typed FAILED — the at-least-once ledger a
        # crash-restart replays
        self.journal = journal
        # fault tolerance (serve/supervisor.py): None = pre-supervision
        # contract — an engine failure resolves every rider with the raw
        # error, no retries (what the direct-API tests pin). With a
        # supervisor, dispatch failures are classified, survivors retried
        # under backoff, poison requests bisected out, and repeated
        # resource failures step the degradation ladder down
        self.supervisor = supervisor
        self._applied_rung = 0
        # (t0, engine_s, bt) of the last FAILED dispatch attempt — written
        # by _dispatch right before it raises, read by the resolvers.
        # Scheduler-thread-only state, like the backend itself
        self._attempt_ctx: tuple = (time.monotonic(), 0.0, None)
        # the batch currently inside the engine (scheduler thread writes,
        # close() snapshots on drain overrun so stuck dispatches still get
        # typed SHUTDOWN sheds instead of hanging their futures)
        self._dispatching: list[ServeRequest] | None = None
        # tracing hub (vnsum_tpu.obs): None = tracing fully off — the hot
        # path then pays only `is None` checks, no allocation, no contextvar
        # writes (the < 2% overhead guarantee in tests/test_obs_serve.py)
        self.obs = obs
        # --trace-dir: host Chrome traces are dumped here by the server, and
        # the FIRST dispatched batch is wrapped in core.profiling.device_profile
        # so one XLA device trace lands side by side with the host spans.
        # That first batch pays the capture cost — trivial on a TPU backend
        # (jax is warm), but ~10s of cold jax import on a FakeBackend dev
        # server — so the capture is one-shot, never per batch
        self._trace_dir = trace_dir
        self._profile_pending = trace_dir is not None
        # multi-tenant QoS (serve/qos.py): the TenantTable arms per-tenant
        # quotas + the weighted-fair pick inside the queue; None = the
        # pre-QoS single-class contract
        self.tenants = tenants
        self.queue = RequestQueue(
            max_depth=max_queue_depth, max_queued_tokens=max_queued_tokens,
            tenants=tenants,
        )
        self.queue.on_shed = self._on_shed
        self.queue.on_admit = self._on_admit
        self.queue.on_take = self._on_take
        # structured jobs (serve/gang.py): gang admission, membership
        # journaling, and degraded-result marking. Always constructed —
        # gang bookkeeping is part of the serving contract; the bench A/B
        # toggles only queue.gang_affinity
        from .gang import GangRegistry

        self.gangs = GangRegistry(journal=journal, metrics=self.metrics)
        if supervisor is not None:
            # brownout gate: at the ladder's bottom rung new EXTERNAL
            # admissions shed with a typed 503 + Retry-After; the gate call
            # doubles as the recovery probe so an idle browned-out server
            # still heals
            self.queue.degraded = supervisor.admission_gate
        # -- request cancellation (DELETE /v1/requests/<id> + disconnects) --
        # trace ids with a standing cancel request, LRU-capped. Written by
        # HTTP handler threads (cancel()), read by the scheduler thread at
        # every lifecycle boundary; keeping ids after their requests resolve
        # is what makes DELETE idempotent (a re-DELETE of a finished cancel
        # answers from here) and closes the submit/cancel race for fan-out
        # siblings that had not reached the queue yet
        self._cancel_lock = make_lock("serve.cancel")
        self._cancelled_ids: OrderedDict[str, str] = OrderedDict()  # guarded by: _cancel_lock
        self.cancel_max_tracked = 4096
        # idle-consumer cancel window: a streaming request whose consumer
        # stopped popping for this long (disconnect with no resume) is
        # cancelled by the sweep. None = disabled (library default; the
        # HTTP server arms it via --stream-idle-timeout-s)
        self.stream_idle_timeout_s: float | None = None
        # bench-only A/B lever (scripts/bench_serving.py cancel phase):
        # False skips the per-iteration cancel sweeps so the unused-path
        # overhead is measurable against the same build. Never exposed as
        # an operator flag — cancellation is part of the serving contract
        self.cancellation_enabled = True
        self._closed = False
        # liveness (serve/watchdog.py): None = unmonitored (the pre-watchdog
        # contract, and the bench A/B's off arm). With a Watchdog, the loop
        # thread registers a heartbeat (beaten from the queue's wait loops,
        # so an idle server still ticks), every engine dispatch is stamped
        # with a token-derived wall-clock budget, and a dispatch past budget
        # is recovered by recover_hung_dispatch ON THE WATCHDOG THREAD:
        # riders resolve typed RequestFailed(HUNG) and this loop thread is
        # REPLACED — the wedged one is fenced off by _stale_thread() checks
        # at every boundary, so its late return can never double-resolve
        self.watchdog = watchdog
        self._hb = None
        if watchdog is not None:
            self._hb = watchdog.register("scheduler", kind="loop")
            self.queue.heartbeat = self._hb.beat
            watchdog.on_hung_dispatch = self.recover_hung_dispatch
        self._thread = threading.Thread(
            target=self._loop, name="vnsum-serve-scheduler", daemon=True
        )
        self._thread.start()

    # -- submission ------------------------------------------------------

    def submit(
        self,
        prompt: str,
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        deadline: float | None = None,
        internal: bool = False,
        reference: str | None = None,
        cache_hint: str | None = None,
        trace: RequestTrace | None = None,
        trace_id: str | None = None,
        trace_owned: bool = False,
        journal_rid: str | None = None,
        tenant: str = "",
        tier: str = "interactive",
        stream=None,
        gang: str = "",
        gang_phase: str = "",
    ):
        """Admit one prompt; returns a Future resolving to a _Completion.
        Raises RequestShed synchronously when admission control rejects.
        ``internal=True`` marks fan-out of already-admitted work (strategy
        rounds riding a QueuedBackend): depth/token admission is skipped —
        the request-level gate is check_admission — while deadline and
        shutdown shedding still apply. ``reference`` rides the request as
        per-row speculation metadata (never part of the batch key);
        ``cache_hint`` rides the same way for the prefix KV cache — it
        bounds backend block insertion AND clusters shared-prefix requests
        into the same engine batch (queue.take_batch). When the backend
        exposes a prefix cache and a token budget is configured, the
        request is billed only its UNCACHED tokens at admission.

        Tracing: an entry point that already owns a RequestTrace (the HTTP
        layer, a strategy's QueuedBackend) passes it via ``trace`` — this
        prompt claims one sub-track on it and the owner finalizes it.
        ``trace_owned=True`` says the caller made the SAMPLING decision,
        whatever it was: with trace=None it means "sampled out", and the
        scheduler must not re-draw per fanned-out prompt (which would both
        distort the configured rate and fragment one request into
        single-prompt traces). Only a bare submit (no owner, ObsHub
        configured) samples here, so direct API users get timelines too.
        ``trace_id`` overrides the queue-derived correlation id either
        way.

        ``journal_rid`` presets the durable-serving ledger id
        (serve/journal.py) — ONLY the startup replay path sets it, so a
        re-enqueued request keeps its original ACCEPT record instead of
        journaling a duplicate.

        ``tenant``/``tier`` are the QoS class (serve/qos.py): the tenant
        bills the token-rate quota and shares via the weighted-fair pick;
        tier "batch" marks the request preemptible in in-flight mode.
        ``stream`` is a serve/stream.StreamChannel the scheduler pushes
        decode-progress text into (the HTTP layer's SSE source).

        ``gang``/``gang_phase`` mark this prompt a member of a structured
        job (serve/gang.py): the queue's take paths cluster same-gang rows
        into one slot generation, the preemption path evicts the group
        whole, and the member joins its gang's journal record at the next
        round flush."""
        req = ServeRequest(
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            config=config,
            reference=reference,
            cache_hint=cache_hint,
            deadline=deadline,
            est_tokens=self.backend.count_tokens(prompt),
            trace_id=trace_id or "",
            journal_rid=journal_rid,
            tenant=tenant,
            tier=tier,
            stream=stream,
            gang_id=gang,
            gang_phase=gang_phase,
        )
        # admission discount: only probed when a token budget exists — the
        # probe re-tokenizes the prompt (a second pass on top of
        # count_tokens above; acceptable because the path is opt-in and a
        # cache-less backend short-circuits before encoding anything)
        if self.queue.max_queued_tokens:
            probe = getattr(self.backend, "cached_prefix_tokens", None)
            if callable(probe):
                req.cached_tokens = min(
                    probe(prompt, cache_hint), req.est_tokens
                )
        if trace is not None:
            req.trace = trace
            req.trace_track = trace.next_track()
        elif not trace_owned and self.obs is not None:
            t = self.obs.start_request(req.trace_id)
            if t is not None:
                req.trace, req.own_trace = t, True
                req.trace_track = t.next_track()
        # the admit is counted by the queue's on_admit hook, under the queue
        # lock, so metrics can never show a completion before its submit
        fut = self.queue.submit(req, force=internal)  # raises RequestShed
        if gang:
            # AFTER admission: the queue's on_admit hook just assigned the
            # ledger id (journal.accept), so the membership note carries it;
            # a shed prompt never joins its gang
            self.gangs.note_member(gang, req.journal_rid, gang_phase)
        return fut

    def check_admission(self, est_tokens: int = 0, tenant: str = "") -> None:
        """Request-level admission gate for entry points that fan out via
        internal submits; sheds are counted in metrics like any other.
        ``tenant`` bills the whole request's tokens against its quota
        bucket here, once — the fan-out's internal submits bill nothing."""
        try:
            self.queue.check_admission(est_tokens, tenant)
        except RequestShed as e:
            self.metrics.observe_shed(e.reason, tenant=tenant)
            if e.reason is ShedReason.QUOTA:
                self.metrics.observe_quota_shed(tenant or "default")
            self._fr("shed", reason=e.reason.value, tenant=tenant)
            raise

    def admit_gang(self, gang_id: str, est_tokens: int = 0,
                   tenant: str = ""):
        """Gang admission (serve/gang.py): ONE pass through the
        request-level admission gate admits the whole fan-out — the tenant
        is billed ``est_tokens`` once, and every internal submit riding the
        returned handle's gang id is admission-exempt. Raises the typed
        RequestShed on rejection (counted like any other shed); on success
        the caller owns the handle and must finish() it when the request
        terminally resolves."""
        self.check_admission(est_tokens, tenant)  # raises RequestShed
        return self.gangs.open(gang_id, tenant=tenant)

    def _on_take(self, batch: list[ServeRequest]) -> None:
        """Queue on_take hook (runs under the queue lock at the take commit
        point): count takes where the affinity pick landed >= 2 siblings of
        one gang in the same batch/slot generation."""
        if len(batch) < 2:
            return
        seen: dict[str, int] = {}
        for r in batch:
            if r.gang_id:
                n = seen.get(r.gang_id, 0) + 1
                if n == 2:
                    self.metrics.observe_gang_affinity_pick()
                seen[r.gang_id] = n

    def _fr(self, kind: str, rid: str = "", **fields) -> None:
        """Flight-recorder append, free when no recorder is armed."""
        if self.recorder is not None:
            self.recorder.record(kind, rid, **fields)

    # -- cancellation -----------------------------------------------------

    def cancel(self, rid: str, *, reason: str = "api",
               force_mark: bool = False) -> dict:
        """Gang-cancel every live request whose trace_id is ``rid`` —
        fan-out children share the parent's trace_id, so one DELETE
        reclaims the whole gang. Queued requests are removed and resolved
        HERE (this thread owns no engine state, and the queue removal is
        atomic under its lock); engine-side residents, taken-but-pending
        requests, and the in-flight one-shot batch are MARKED and reclaimed
        by the scheduler thread at the next segment boundary (the engine is
        single-threaded by contract — only its thread may touch slots).

        Idempotent: a rid already marked (or already terminal) re-answers
        with zero counts. ``force_mark`` marks even when nothing live
        matches — the server uses it when the JOURNAL still holds a
        non-terminal entry for ``rid`` (a handoff window this thread
        cannot see into), so the mark is guaranteed to be observed.
        Returns {"cancelled_queued", "cancel_pending", "known"}."""
        with self._cancel_lock:
            already = rid in self._cancelled_ids
        removed = self.queue.cancel_where(lambda r: r.trace_id == rid)
        # racy read of scheduler-thread state for the COUNT only (stale =
        # off by one, never a crash); the authoritative reclaim runs on the
        # scheduler thread at the next segment boundary
        pending = [] if self.cancellation_enabled is False else [
            r for r in self._stranded_snapshot() if r.trace_id == rid
        ]
        known = bool(removed or pending or already)
        if known or force_mark:
            with self._cancel_lock:
                self._cancelled_ids[rid] = reason
                self._cancelled_ids.move_to_end(rid)
                while len(self._cancelled_ids) > self.cancel_max_tracked:
                    self._cancelled_ids.popitem(last=False)
        for r in removed:
            self._resolve_cancelled(r, "queued", reason)
        return {
            "cancelled_queued": len(removed),
            "cancel_pending": len(pending),
            "known": known,
        }

    def _cancel_reason_for(self, r: ServeRequest) -> str | None:
        """The standing cancel reason for ``r`` (gang-marked trace id or an
        idle streaming consumer), or None. The unlocked emptiness probe is
        the fast path: with no cancels and no idle window armed this is two
        attribute reads per call."""
        if not self.cancellation_enabled:
            return None
        # lint-allow[guarded-by]: unlocked EMPTINESS probe only — a stale read delays detection by one boundary; the authoritative lookup below holds the lock
        if self._cancelled_ids:
            with self._cancel_lock:
                reason = self._cancelled_ids.get(r.trace_id)
            if reason is not None:
                return reason
        t = self.stream_idle_timeout_s
        if (
            t is not None
            and r.stream is not None
            and r.stream.idle_for() > t
        ):
            return "disconnect"
        return None

    def _cancel_sweep(self) -> None:
        """Scheduler-thread sweep at lifecycle boundaries: pull cancelled
        (or consumer-abandoned) requests out of the queue and resolve them.
        Residents/pending are swept by the in-flight subclass; the one-shot
        batch is checked inside _dispatch."""
        if not self.cancellation_enabled:
            return
        # lint-allow[guarded-by]: unlocked EMPTINESS probe only — the per-iteration fast path; a stale read delays one sweep, the matching reads hold the lock
        if not self._cancelled_ids and self.stream_idle_timeout_s is None:
            return  # unlocked fast path: nothing can match
        removed = self.queue.cancel_where(
            lambda r: self._cancel_reason_for(r) is not None
        )
        for r in removed:
            self._resolve_cancelled(
                r, "queued", self._cancel_reason_for(r) or "disconnect"
            )

    def _resolve_cancelled(self, r: ServeRequest, stage: str,
                           reason: str = "api", *,
                           taken: bool = False) -> None:
        """Terminal cancellation bookkeeping — the one funnel every cancel
        path ends in: metrics (stage-labeled; disconnect-triggered ones
        counted separately), QoS unwind for work the engine never ran
        (token bucket back-fill; DRR deficit too when ``taken`` — the take
        commit point had charged it), preempt-pin release, the typed
        CANCELLED ledger record, the owned-trace finalization, the stream
        close, and the future."""
        self.metrics.observe_cancel(stage, tenant=r.tenant)
        if reason == "disconnect":
            self.metrics.observe_cancel_disconnect()
        self._fr("cancel", rid=r.trace_id, stage=stage, reason=reason)
        if self.tenants is not None and stage == "queued":
            # never dispatched: the admission bill buys nothing — return it
            # (queue-resident requests never charged DRR, so deficit credit
            # only applies to taken-but-undispatched ones)
            self.tenants.refund(r.tenant, r.billable_tokens, deficit=taken)
        self._release_preempt_pins(r)
        self._journal_cancel(r, reason)
        if r.own_trace and r.trace is not None and self.obs is not None:
            self.obs.finish_request(r.trace, f"cancelled:{reason}")
            r.trace = None
        if r.stream is not None:
            # deltas already buffered stay poppable until close; a consumer
            # that is still attached sees the future's typed exception as
            # its terminal event, one that is gone stops costing memory
            r.stream.close()
        if not r.future.done():
            try:
                r.future.set_exception(RequestCancelled(stage, reason))
            # lint-allow[swallowed-exception]: losing the done()-check race means the scheduler thread resolved this future first — it is already answered, and the cancel sweep must keep going for the rest
            except InvalidStateError:
                pass

    def _journal_cancel(self, r: ServeRequest, reason: str) -> None:
        if self.journal is not None and r.journal_rid is not None:
            self.journal.cancel(r.journal_rid, reason)

    def submit_many(self, prompts, references=None, cache_hints=None, **kw):
        """Admit a round of prompts atomically-ish: if any prompt is shed at
        admission, already-admitted siblings are left to complete (they
        occupy queue slots either way) and the shed propagates to the
        caller — a strategy round is all-or-nothing for its caller.
        ``references`` optionally aligns one speculation reference per
        prompt; ``cache_hints`` one prefix-cache hint per prompt."""
        if references is None:
            references = [None] * len(prompts)
        if cache_hints is None:
            cache_hints = [None] * len(prompts)
        return [
            self.submit(p, reference=r, cache_hint=h, **kw)
            for p, r, h in zip(prompts, references, cache_hints)
        ]

    def generate_sync(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        deadline: float | None = None,
        internal: bool = False,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
        trace: RequestTrace | None = None,
        trace_id: str | None = None,
        trace_owned: bool = False,
        tenant: str = "",
        tier: str = "interactive",
        gang: str = "",
        gang_phase: str = "",
    ) -> list[_Completion]:
        futs = self.submit_many(
            prompts, references=references, cache_hints=cache_hints,
            max_new_tokens=max_new_tokens,
            config=config, deadline=deadline, internal=internal,
            trace=trace, trace_id=trace_id, trace_owned=trace_owned,
            tenant=tenant, tier=tier, gang=gang, gang_phase=gang_phase,
        )
        # lint-allow[unbounded-blocking-wait]: externally bounded — these are request futures EVERY scheduler path resolves (success, typed failure, shed; drain-overrun sheds cover even a wedged engine, and the watchdog resolves hung dispatches typed)
        return [f.result() for f in futs]

    def backend_view(
        self,
        deadline: float | None = None,
        trace: RequestTrace | None = None,
        trace_id: str | None = None,
        tenant: str = "",
        tier: str = "interactive",
        gang: str = "",
    ) -> "QueuedBackend":
        """A Backend-protocol view whose generate() routes through this
        scheduler — hand it to a strategy to make its rounds coalesce with
        everyone else's. A ``trace`` makes every round's prompt record its
        spans on that ONE request timeline (per-prompt sub-tracks).
        ``tenant``/``tier`` stamp every fanned-out prompt with the
        request's QoS class, so a batch-tier summarize's map round stays
        preemptible and WFQ-scheduled. ``gang`` (serve/gang.py) stamps
        every fanned-out prompt with the request's structured-job id AND
        unlocks the view's streaming submit_round/harvest protocol for
        strategies that overlap their reduce with the map fan-out."""
        return QueuedBackend(self, deadline=deadline, trace=trace,
                             trace_id=trace_id, tenant=tenant, tier=tier,
                             gang=gang)

    # -- scheduler thread ------------------------------------------------

    def _on_admit(self, req: ServeRequest) -> None:
        """Queue on_admit hook (runs under the queue lock): count the
        submit and, when durable serving is on, write the ACCEPT record —
        BEFORE the scheduler can take the request, so no engine work ever
        happens on an unjournaled request."""
        self.metrics.observe_submit(tenant=req.tenant)
        if self.tenants is not None:
            self.metrics.observe_tenant_request(req.tenant or "default")
        if req.stream is not None:
            self.metrics.observe_stream_request()
        if self.journal is not None:
            self.journal.accept(req)
        self._fr("admit", rid=req.trace_id, tenant=req.tenant,
                 tokens=req.est_tokens)

    def _journal_fail(self, req: ServeRequest, reason: str,
                      detail: str = "") -> None:
        """Typed-FAILED ledger append for every terminal non-success path.
        journal_rid is None for requests shed AT admission (they were never
        accepted, so the ledger owes them nothing) and when journaling is
        off."""
        if self.journal is not None and req.journal_rid is not None:
            self.journal.fail(req.journal_rid, reason, detail)

    def _on_shed(self, req: ServeRequest, reason: ShedReason) -> None:
        self.metrics.observe_shed(reason, tenant=req.tenant)
        if reason is ShedReason.QUOTA:
            self.metrics.observe_quota_shed(req.tenant or "default")
        self._fr("shed", rid=req.trace_id, reason=reason.value,
                 tenant=req.tenant)
        self._release_preempt_pins(req)
        self._journal_fail(req, f"shed:{reason.value}")
        # scheduler-owned traces must not leak open on the shed path; the
        # hub lock is independent of the queue lock this hook runs under
        if req.own_trace and req.trace is not None and self.obs is not None:
            self.obs.finish_request(req.trace, f"shed:{reason.value}")
            req.trace = None

    def _take_limit(self) -> int:
        """Engine dispatch width: the configured max_batch, halved by the
        degradation ladder from REDUCED_BATCH down."""
        if self.supervisor is not None:
            return self.supervisor.batch_limit(self.max_batch)
        return self.max_batch

    def _stale_thread(self) -> bool:
        """True on a scheduler thread the watchdog has REPLACED: its
        dispatch was declared hung, its riders were already resolved typed,
        and a successor owns the loop — every boundary checks this so the
        abandoned thread exits without touching shared state."""
        return threading.current_thread() is not self._thread

    def _requeue_stale(self, requests) -> None:
        """A stale thread observing the fence while still HOLDING taken
        work hands it back — never drops it. The case this exists for: a
        falsely-hung dispatch (slow but alive) returns in the declaration
        window, resolves its own riders, and takes a FRESH batch off the
        queue before the fence flips; dropping that batch at the next
        stale check would strand its futures forever, the one outcome this
        package forbids. Requeue is safe here: these futures are
        unresolved (the true-hang case resolved everything via recovery,
        making this a no-op), queue.requeue admits even after close, and
        the successor applies deadline discipline as usual."""
        n = 0
        for r in requests:
            if not r.future.done():
                self.queue.requeue(r)
                n += 1
        if n:
            logger.warning(
                "stale scheduler thread handed %d taken request(s) back "
                "to the queue for the successor", n,
            )

    def _loop(self) -> None:
        while True:
            if self._stale_thread():
                return  # replaced by watchdog recovery; the successor runs
            if self._hb is not None:
                self._hb.beat()
            try:
                self._cancel_sweep()
                batch = self.queue.take_batch(self._take_limit(),
                                              self.max_wait_s)
            # lint-allow[swallowed-exception]: a queue bug must not kill the scheduler thread; no request was taken, so there is no future to resolve
            except Exception:  # pragma: no cover - queue bugs must not kill serving
                logger.exception("take_batch failed; scheduler continuing")
                continue
            if batch is None:
                # closed and drained: a cleanly-exited loop must stop being
                # monitored — a drained scheduler is not a stall
                if self.watchdog is not None and not self._stale_thread():
                    self.watchdog.unregister("scheduler")
                return
            try:
                self._run_batch(batch)
            except Exception as e:  # pragma: no cover - belt and braces
                # _run_batch guards backend.generate, but anything raising
                # after it (token counting, metrics) must not kill the
                # scheduler thread: callers block on these futures forever
                # and /healthz would keep reporting ok
                logger.exception("batch post-processing failed")
                for r in batch:
                    self._journal_fail(r, "error", str(e))
                    if not r.future.done():
                        r.future.set_exception(e)

    def _run_batch(self, batch: list[ServeRequest]) -> None:
        """One coalesced batch, end to end. With a supervisor configured,
        dispatch failures go through classify -> retry/bisect -> typed
        resolution (_run_supervised); without one, a failure resolves every
        rider with the raw error — the pre-supervision contract."""
        self._dispatching = batch
        try:
            if self.supervisor is None:
                try:
                    self._dispatch(batch)
                except Exception as e:
                    if self._stale_thread():
                        # true hang: recovery resolved these typed HUNG (a
                        # no-op requeue); false positive: hand them back
                        self._requeue_stale(batch)
                        return
                    self._resolve_errored(batch, e, *self._attempt_ctx)
                return
            self._run_supervised(batch)
        finally:
            # identity-guarded: an abandoned thread waking from a hung
            # dispatch must not null out the SUCCESSOR's live batch
            if self._dispatching is batch:
                self._dispatching = None

    def _dispatch(self, batch: list[ServeRequest]) -> None:
        """One engine dispatch: resolves every future on success; on failure
        records the attempt's batch metrics/trace, stashes (t0, engine_s,
        bt) in ``_attempt_ctx`` for the resolvers, and raises."""
        # cancelled riders leave BEFORE engine work: they were taken off the
        # queue (DRR charged), so the queued-stage resolution credits it back
        live = []
        for r in batch:
            reason = self._cancel_reason_for(r)
            if reason is not None:
                self._resolve_cancelled(r, "queued", reason, taken=True)
            else:
                live.append(r)
        batch[:] = live
        if not batch:
            return
        head = batch[0]
        self._attempt_ctx = (time.monotonic(), 0.0, None)
        if self.recorder is not None:
            # guarded, not _fr: the riders list must not be built on the
            # recorder-less hot path (the all-off arm's contract)
            self.recorder.record("dispatch", rid=head.trace_id,
                                 occupancy=len(batch),
                                 rids=[r.trace_id for r in batch[1:]])
        if self.journal is not None:
            # START marks "engine work began" — replay after a crash here
            # recomputes from the ACCEPT payload (deterministic greedy), so
            # START is bookkeeping for operators, not a correctness gate
            for r in batch:
                if r.journal_rid is not None:
                    self.journal.start(r.journal_rid)
        # batch telemetry (vnsum_tpu.obs): the BatchTrace is installed as the
        # contextvar collector for the duration of backend.generate, so the
        # engine's prefill/decode/spec-step emits land on THIS batch's track
        # and its prefill end anchors every rider's TTFT
        bt = self.obs.start_batch(len(batch)) if self.obs is not None else None
        profile_cm = contextlib.nullcontext()
        if self._profile_pending:
            # one-shot: the first dispatched batch also captures an XLA
            # device profile into --trace-dir, side by side with host spans
            self._profile_pending = False
            from ..core.profiling import device_profile

            profile_cm = device_profile(self._trace_dir)
        references = [r.reference for r in batch]
        if self.supervisor is not None and not self.supervisor.spec_enabled:
            # ladder rung NO_SPEC: drop speculation references so the engine
            # takes the plain decode path (greedy outputs are identical)
            references = [None] * len(batch)
        token = set_collector(bt) if bt is not None else None
        # cooperative cancel flag for the blocking one-shot program:
        # backends that expose set_cancel_poll check it at their segment
        # boundaries and stop burning device time once EVERY rider is
        # cancelled (a partial cancel can't shrink a fixed batch mid-
        # flight; the riders resolve typed after the dispatch returns).
        # The poll runs on THIS thread inside generate — _cancelled ids are
        # read under their own lock, no engine state is touched
        set_poll = getattr(self.backend, "set_cancel_poll", None)
        if callable(set_poll) and self.cancellation_enabled:
            set_poll(lambda: all(
                self._cancel_reason_for(r) is not None for r in batch
            ))
        ticket = self._wd_begin("one_shot", batch)
        t0 = time.monotonic()
        try:
            with profile_cm:
                outs = self.backend.generate(
                    [r.prompt for r in batch],
                    max_new_tokens=head.max_new_tokens,
                    config=head.config,
                    references=references,
                    cache_hints=[r.cache_hint for r in batch],
                )
        except Exception:
            engine_s = time.monotonic() - t0
            if self._stale_thread():
                # this dispatch was declared HUNG and the riders resolved
                # by the watchdog; the late error belongs to nobody
                raise
            self._finish_batch_trace(bt, 0)
            self.metrics.observe_batch(len(batch), engine_s)
            logger.exception("engine batch of %d failed", len(batch))
            self._attempt_ctx = (t0, engine_s, bt)
            raise
        finally:
            self._wd_end(ticket)
            if token is not None:
                reset_collector(token)
            if (callable(set_poll) and self.cancellation_enabled
                    and not self._stale_thread()):
                # a stale thread must not clear the SUCCESSOR's poll
                set_poll(None)
        if self._stale_thread():
            # the watchdog already resolved every rider typed HUNG and a
            # successor thread owns the loop: the late result is discarded
            # (future.done() guards would drop it anyway — skipping the
            # bookkeeping keeps metrics and the journal single-counted).
            # Belt and braces for the fence-mid-bookkeeping window: any
            # rider recovery did NOT resolve goes back to the queue
            self._requeue_stale(batch)
            return
        engine_s = time.monotonic() - t0
        if len(outs) != len(batch):
            # a zip would silently drop the tail and strand its futures
            e = RuntimeError(
                f"backend returned {len(outs)} outputs for a batch of "
                f"{len(batch)}"
            )
            logger.error(str(e))
            self._finish_batch_trace(bt, 0)
            self.metrics.observe_batch(len(batch), engine_s)
            self._attempt_ctx = (t0, engine_s, bt)
            raise e
        gen_tokens = self.backend.count_tokens_batch(outs)
        self._finish_batch_trace(bt, sum(gen_tokens))
        self.metrics.observe_batch(len(batch), engine_s, sum(gen_tokens))
        # per-request speculative-decoding attribution: backends with the
        # spec path expose take_spec_report() — per-prompt records aligned
        # with the batch, cleared on read. Engine access is single-threaded
        # (this scheduler thread), so read-after-generate cannot race.
        take_spec = getattr(self.backend, "take_spec_report", None)
        spec_report = take_spec() if callable(take_spec) else []
        if len(spec_report) != len(batch):
            spec_report = [None] * len(batch)
        # prefix-cache attribution rides the same read-after-generate hook:
        # per-prompt cached prefill tokens, aligned with the batch
        take_cache = getattr(self.backend, "take_cache_report", None)
        cache_report = take_cache() if callable(take_cache) else []
        if len(cache_report) != len(batch):
            cache_report = [0] * len(batch)
        for r, out, n_out, spec, cached in zip(
            batch, outs, gen_tokens, spec_report, cache_report
        ):
            reason = self._cancel_reason_for(r)
            if reason is not None:
                # cancelled while the batch was in the engine: the decode
                # work is sunk, but the outcome is typed CANCELLED — never
                # COMPLETE (the DELETE contract: a cancelled id must not
                # resurrect at replay or answer the poll surface as done)
                self._resolve_cancelled(r, "dispatched", reason)
                continue
            rec = self._record(r, "ok", t0, engine_s, len(batch), n_out, bt)
            if spec is not None:
                rec.draft_tokens = spec.draft_tokens
                rec.accepted_tokens = spec.accepted_tokens
                rec.spec_steps = spec.verify_steps
            rec.cached_prompt_tokens = int(cached)
            self.metrics.observe_request(rec, tenant=r.tenant)
            self._fr("complete", rid=r.trace_id, gen_tokens=n_out)
            self._trace_request(r, t0, engine_s, bt, "ok")
            self._release_preempt_pins(r)
            if r.stream is not None:
                # the one-shot program has no observable mid-decode
                # boundary: the whole text leaves as one delta, BEFORE the
                # future resolves so the handler's drain-after-done sees it
                r.stream.push_text(out)
            if self.journal is not None and r.journal_rid is not None:
                # journal COMPLETE before resolving the future: a success
                # the client saw is always in the ledger (a crash between
                # replays the request and re-completes it identically)
                self.journal.complete(r.journal_rid, out, n_out)
            if not r.future.done():
                r.future.set_result(_Completion(out, rec))

    # -- watchdog (serve/watchdog.py) -------------------------------------

    # decode-token assumption for dispatch budgets when a request carries no
    # explicit max_new_tokens (the backend default is not visible here);
    # budgets are ceilings, not estimates, so generous is correct
    WATCHDOG_DEFAULT_NEW_TOKENS = 256

    def _wd_begin(self, kind: str, batch: list[ServeRequest]):
        """Stamp one engine dispatch with its wall-clock budget (the
        bounded-dispatch contract): prompt tokens plus the decode ceiling,
        through the watchdog's base+per-token formula. None when
        unmonitored — the healthy path pays one `is None` check."""
        wd = self.watchdog
        if wd is None:
            return None
        head = batch[0]
        tokens = sum(r.est_tokens for r in batch) + len(batch) * (
            head.max_new_tokens or self.WATCHDOG_DEFAULT_NEW_TOKENS
        )
        return wd.begin_dispatch(
            "scheduler", kind, wd.dispatch_budget(tokens),
            riders=tuple(r.trace_id for r in batch), tokens=tokens,
        )

    def _wd_end(self, ticket) -> None:
        if ticket is not None:
            self.watchdog.end_dispatch(ticket)

    def recover_hung_dispatch(self, ticket) -> None:
        """Wedged-dispatch recovery — runs ON THE WATCHDOG THREAD while the
        scheduler thread is still parked inside the engine call it will
        never (or too late) return from. Everything touched here is
        thread-safe by construction (futures, the journal, metrics, the
        queue) or parked-thread state the fences make safe to read.

        One-shot dispatch: every unresolved rider fails typed
        ``RequestFailed(HUNG)`` — retryable from the client's seat, typed
        FAILED in the ledger (the journal replay can't resurrect work whose
        dispatch wedged the engine). The ladder takes a resource strike and
        the loop thread is replaced; the abandoned one is fenced by
        ``_stale_thread()`` at every boundary. The in-flight subclass
        overrides the slot-loop kinds to REQUEUE instead (the hang there is
        the loop's fault, not the riders')."""
        from .supervisor import FailureClass, RequestFailed

        # FENCE FIRST: installing the (unstarted) successor flips
        # _stale_thread() for the wedged thread before any shared state is
        # touched — a dispatch that limps back at budget+epsilon hits a
        # stale check at its next boundary instead of racing this recovery
        # (the residual window is the boundary check itself; future.done()
        # guards and the journal's terminal no-ops bound what a loser of
        # that race can do to double-bookkeeping, never corruption)
        successor = self._fence_replacement()
        riders = [r for r in (self._dispatching or [])
                  if not r.future.done()]
        exc = RequestFailed(
            FailureClass.HUNG,
            detail=(f"engine dispatch exceeded its {ticket.budget_s:.1f}s "
                    f"watchdog budget ({ticket.kind})"),
        )
        if riders:
            logger.critical(
                "watchdog recovery: failing %d rider(s) of the hung %s "
                "dispatch typed HUNG", len(riders), ticket.kind,
            )
            # clock discipline: ticket timestamps live in the WATCHDOG's
            # clock space (synthetic under test) — derive the stall age
            # there, then anchor the record in this scheduler's monotonic
            # space so queue-wait math against enqueued_at stays coherent
            age = max(self.watchdog.now() - ticket.started_at, 0.0)
            t0 = time.monotonic() - age
            self._resolve_errored(riders, exc, t0, age, None)
        self._note_hang_strike()
        self._start_replacement(successor)

    def _note_hang_strike(self) -> None:
        """A hang is too-hot-operating-point evidence like an OOM: the
        degradation ladder takes a resource-class strike."""
        from .supervisor import FailureClass

        sup = self.supervisor
        if sup is None:
            return
        self.metrics.observe_failure(FailureClass.HUNG.value)
        sup.note_failure(FailureClass.HUNG)
        # rung EFFECTS still apply lazily on the (new) engine thread at its
        # next dispatch — _apply_rung stays scheduler-thread-only

    def _fence_replacement(self) -> threading.Thread:
        """Create the successor loop thread WITHOUT starting it and install
        it as ``self._thread`` — reassignment IS the fence: from this
        instant the wedged thread reads ``_stale_thread() == True`` at
        every boundary and exits without touching shared state (its
        in-flight engine call is sunk cost). Recovery mutates shared state
        between this call and ``_start_replacement``, single-threaded."""
        t = threading.Thread(
            target=self._loop, name="vnsum-serve-scheduler", daemon=True
        )
        self._thread = t
        return t

    def _start_replacement(self, successor: threading.Thread) -> None:
        """Recovery's last act: re-beat the heartbeat (the successor must
        not start life already stalled) and let it serve."""
        if self._hb is not None:
            self._hb.beat()
        successor.start()
        logger.warning("watchdog recovery: scheduler thread replaced")

    # -- supervision (serve/supervisor.py) --------------------------------

    def _run_supervised(self, batch: list[ServeRequest]) -> None:
        """Dispatch with recovery, entirely on the scheduler thread: every
        path resolves every future. ``work`` is a stack of sub-batches —
        retries and bisection halves go back on it until everything is
        resolved (success, typed failure, or shed)."""
        sup = self.supervisor
        work: list[list[ServeRequest]] = [batch]
        while work:
            if self._stale_thread():
                # watchdog recovery owns the hung dispatch's riders; any
                # OTHER unresolved work this thread still holds (a batch
                # taken in the declaration window) goes back to the queue
                self._requeue_stale([r for g in work for r in g])
                return
            group = [r for r in work.pop() if not r.future.done()]
            # deadline discipline survives retries: an expired rider is
            # shed typed, never redispatched
            now = time.monotonic()
            for r in [r for r in group if r.expired(now)]:
                self._shed_taken(r, ShedReason.DEADLINE)
            group = [r for r in group if not r.expired(now)]
            if not group:
                continue
            # ladder rung REDUCED_BATCH+: never dispatch wider than the
            # degraded limit, even for batches taken before the step-down
            limit = sup.batch_limit(self.max_batch)
            if len(group) > limit:
                work.append(group[limit:])
                group = group[:limit]
            self._apply_rung()
            try:
                self._dispatch(group)
                sup.record_success()
                self._apply_rung()
            except Exception as e:
                if self._stale_thread():
                    # late error from a dispatch already declared HUNG —
                    # recovery resolved ITS riders; hand anything else back
                    self._requeue_stale(
                        [r for g in work for r in g] + group
                    )
                    return
                self._resolve_dispatch_failure(group, e, work)

    def _resolve_dispatch_failure(
        self, group: list[ServeRequest], e: Exception,
        work: list[list[ServeRequest]],
    ) -> None:
        """Decide each rider's fate after one failed dispatch: fail typed
        (fatal / out of budget / poisoned alone), bisect to isolate, or push
        a backed-off retry onto ``work``."""
        from .supervisor import FailureClass

        sup = self.supervisor
        cls = sup.classify(e)
        self.metrics.observe_failure(cls.value)
        self._fr("fault", rid=group[0].trace_id, failure_class=cls.value,
                 group=len(group))
        sup.note_failure(cls)
        self._apply_rung()
        if cls is FailureClass.FATAL:
            self._resolve_failed(group, e, cls)
            return
        if cls is FailureClass.POISON:
            # deterministic input error: retrying burns device time. Alone,
            # the request IS the poison — quarantine typed; in company,
            # bisect so innocent riders escape through the clean half
            if len(group) == 1:
                self.metrics.observe_quarantine()
                self._dump("quarantine")
                self._resolve_failed(group, e, cls)
            else:
                self._bisect(group, work)
            return
        # TRANSIENT / RESOURCE: charge the failed attempt to every rider
        for r in group:
            r.attempts += 1
        budget = sup.policy.max_attempts
        if any(r.attempts >= budget for r in group):
            if len(group) > 1:
                # the group burned its budget together — quarantine by
                # bisection instead of failing innocents with the
                # stranger's error
                self._bisect(group, work)
                return
            # a lone request out of budget is terminal. A TRANSIENT-class
            # error that failed every attempt, finally with no one else to
            # blame, is the quarantine verdict; RESOURCE keeps its class
            # (the operating point, not the request, is at fault)
            final = (FailureClass.POISON if cls is FailureClass.TRANSIENT
                     else cls)
            if final is FailureClass.POISON:
                self.metrics.observe_quarantine()
                self._dump("quarantine")
            self._resolve_failed(group, e, final)
            return
        delay = sup.backoff_s(max(r.attempts for r in group))
        self.metrics.observe_retry(len(group))
        self.metrics.observe_backoff(delay)
        for r in group:
            self._trace_fault(r, "retry", cls.value, delay)
        logger.warning(
            "retrying batch of %d after %s failure (backoff %.3fs)",
            len(group), cls.value, delay,
        )
        # the backoff sleeps the scheduler thread: queued healthy work waits
        # it out too, which is deliberate — the engine just failed, and
        # hammering it with the next batch is how failure storms start
        time.sleep(delay)
        work.append(group)

    def _bisect(self, group: list[ServeRequest],
                work: list[list[ServeRequest]]) -> None:
        """Split a crashing batch to isolate its poison: halves re-dispatch
        independently; the culprit bottoms out alone and fails typed while
        every innocent rider escapes through a clean half."""
        self.metrics.observe_bisect()
        self._fr("bisect", rid=group[0].trace_id, group=len(group))
        mid = len(group) // 2
        logger.warning(
            "bisecting crashing batch of %d to quarantine the fault",
            len(group),
        )
        for r in group:
            self._trace_fault(r, "bisect", None, 0.0)
        work.append(group[mid:])
        work.append(group[:mid])

    def _dump(self, reason: str) -> None:
        """Anomaly-triggered flight-recorder dump (no-op without one)."""
        if self.recorder is not None:
            self.recorder.dump(reason)

    def _resolve_failed(self, group, e, failure_class) -> None:
        """Terminal typed failure: every rider's future gets RequestFailed
        carrying the class and the last underlying error."""
        from .supervisor import FailureClass, RequestFailed

        if failure_class is FailureClass.FATAL:
            # the engine itself is gone: snapshot the black box while the
            # lead-up is still in the ring
            self._dump("fatal")
        t0, engine_s, bt = self._attempt_ctx
        exc = RequestFailed(failure_class, detail=str(e), cause=e)
        self._resolve_errored(group, exc, t0, engine_s, bt)

    def _release_preempt_pins(self, r: ServeRequest) -> None:
        """Drop the prefix-cache pins a preemption took (serve/inflight.py):
        the blocks were held so a preempted request's cached prefix
        survives LRU until it terminally resolves — every resolution path
        (complete, errored, shed) funnels through here. Idempotent."""
        pins, r.preempt_pins = r.preempt_pins, []
        for cache, match in pins:
            cache.release(match)

    def _shed_taken(self, r: ServeRequest, reason: ShedReason) -> None:
        """Typed shed for a request already taken off the queue (deadline
        expiry at retry, drain overrun): metrics + owned-trace finalization
        + the future, mirroring the queue-side shed hook."""
        self.metrics.observe_shed(reason, tenant=r.tenant)
        self._fr("shed", rid=r.trace_id, reason=reason.value,
                 tenant=r.tenant)
        self._release_preempt_pins(r)
        self._journal_fail(r, f"shed:{reason.value}")
        if r.own_trace and r.trace is not None and self.obs is not None:
            self.obs.finish_request(r.trace, f"shed:{reason.value}")
            r.trace = None
        if not r.future.done():
            try:
                r.future.set_exception(RequestShed(reason))
            # lint-allow[swallowed-exception]: losing the done()-check race means the scheduler thread resolved this future first — it is already answered, and the shed loop must keep going for the rest
            except InvalidStateError:
                pass

    def _trace_fault(self, r: ServeRequest, event: str,
                     failure_class: str | None, delay: float) -> None:
        """Fault-path observability on the request's own timeline: one span
        per retry/bisect so /debug/trace shows WHY a request's e2e latency
        grew (class + attempt count + backoff)."""
        tr = r.trace
        if tr is None:
            return
        args = {"attempts": r.attempts}
        if failure_class:
            args["failure_class"] = failure_class
        tr.add(f"fault_{event}", time.monotonic(), delay, r.trace_track,
               **args)

    def _apply_rung(self) -> None:
        """Lazily apply ladder effects on the engine thread (the backend is
        not thread-safe, so rung changes noted elsewhere take effect at the
        next dispatch): prefix-cache insert gating, the step counters, and
        the transition log line."""
        sup = self.supervisor
        rung = int(sup.rung)
        if rung == self._applied_rung:
            return
        down = rung > self._applied_rung
        for _ in range(abs(rung - self._applied_rung)):
            self.metrics.observe_degraded(down)
        logger.warning(
            "degradation ladder: rung %d -> %d (%s)",
            self._applied_rung, rung, "step-down" if down else "recovery",
        )
        self._fr("rung_change", from_rung=self._applied_rung, to_rung=rung)
        from .supervisor import Rung

        if down and rung >= Rung.BROWNOUT:
            # brownout entry is the post-mortem moment: dump the ring with
            # the failure storm that drove the ladder down still in it
            self._dump("brownout")
        self._applied_rung = rung
        toggle = getattr(self.backend, "set_prefix_cache_inserts", None)
        if callable(toggle):
            toggle(sup.cache_inserts_enabled)

    def _resolve_errored(self, batch, e, t0, engine_s, bt) -> None:
        from .supervisor import RequestFailed

        reason = (
            e.failure_class.value if isinstance(e, RequestFailed) else "error"
        )
        for r in batch:
            rec = self._record(r, "error", t0, engine_s, len(batch), 0, bt)
            self.metrics.observe_request(rec, tenant=r.tenant)
            self._fr("failed", rid=r.trace_id, reason=reason)
            self._trace_request(r, t0, engine_s, bt, "error")
            self._release_preempt_pins(r)
            self._journal_fail(r, reason, str(e))
            if not r.future.done():
                r.future.set_exception(e)

    def _finish_batch_trace(self, bt, gen_tokens: int) -> None:
        if bt is not None:
            self.obs.finish_batch(bt, gen_tokens)

    def _trace_request(self, r: ServeRequest, t0: float, engine_s: float,
                       bt, status: str) -> None:
        """Append this dispatch's spans to the request's trace: queue wait,
        engine residency (tagged with the batch it rode), postprocess
        (detokenize-side token counting + record assembly). One call per
        (request, batch) — a summarize request accumulates one span triple
        per strategy-round prompt, each on its own sub-track."""
        tr = r.trace
        if tr is None:
            return
        track = r.trace_track
        t1 = t0 + engine_s
        tr.add("queue_wait", r.enqueued_at, max(t0 - r.enqueued_at, 0.0),
               track, request_id=r.request_id)
        tr.add("engine", t0, engine_s, track, status=status,
               batch=bt.batch_id if bt is not None else None,
               occupancy=bt.occupancy if bt is not None else None)
        tr.add("postprocess", t1, max(time.monotonic() - t1, 0.0), track)
        if r.own_trace and self.obs is not None:
            self.obs.finish_request(tr, status)

    def _record(self, r, status, t0, engine_s, batch_size, gen_tokens,
                bt=None):
        now = time.monotonic()
        # TTFT anchor: the batch's host-observed prefill end when the
        # backend emitted one; the fused one-shot program has no observable
        # midpoint, so the whole engine call is the honest upper bound —
        # reported in the record but EXCLUDED from the TTFT histogram
        # (metrics.observe_request keys on ttft_anchored)
        anchored = bt is not None and bt.first_token_at is not None
        first_token = bt.first_token_at if anchored else t0 + engine_s
        return ServeRequestRecord(
            request_id=r.request_id,
            status=status,
            trace_id=r.trace_id,
            queue_wait_s=max(t0 - r.enqueued_at, 0.0),
            engine_s=engine_s,
            total_s=max(now - r.enqueued_at, 0.0),
            ttft_s=max(first_token - r.enqueued_at, 0.0),
            ttft_anchored=anchored,
            batch_size=batch_size,
            prompt_tokens=r.est_tokens,
            generated_tokens=gen_tokens,
        )

    # -- lifecycle -------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admitting; drain=True runs remaining queued batches to
        completion before the scheduler thread exits.

        Drain overrun is not a warning-and-hang: when the scheduler thread
        (stuck dispatch, fault storm) misses the window, every still-queued
        AND currently-dispatching request gets a typed
        RequestShed(SHUTDOWN) on its future — callers blocked on result()
        unblock with the shed instead of hanging forever. The thread is a
        daemon and every resolution site guards future.done(), so a late
        engine completion is dropped harmlessly."""
        self._closed = True
        # drain beats an in-flight SLEEP: backends with a simulated latency
        # model (FakeBackend, and the injected `latency` fault kind) abort
        # their sleeps on request_drain, so a graceful SIGTERM never waits
        # out fake device time — outputs are unaffected (the sleep is pure
        # simulation), only the wall clock shrinks. Real backends simply
        # don't expose the hook
        drain_hook = getattr(self.backend, "request_drain", None)
        if callable(drain_hook):
            drain_hook()
        self.queue.close(drain=drain)
        self._thread.join(timeout=timeout)
        if self.watchdog is not None:
            # closed (drained or overrun): either way this scheduler stops
            # being monitored — shutdown must not read as a stall
            self.watchdog.unregister("scheduler")
        if self._thread.is_alive():
            shed_queued = self.queue.shed_pending()
            stranded = self._stranded_snapshot()
            for r in stranded:
                self._shed_taken(r, ShedReason.SHUTDOWN)
            logger.warning(
                "scheduler did not drain within %.1fs; shed %d queued and "
                "%d in-flight request(s) with typed SHUTDOWN",
                timeout, shed_queued, len(stranded),
            )

    def _stranded_snapshot(self) -> list[ServeRequest]:
        """Requests taken off the queue but not yet resolved — what a drain
        overrun must shed. The in-flight subclass adds its resident slots."""
        return list(self._dispatching or [])

    @property
    def closed(self) -> bool:
        return self._closed


class QueuedBackend:
    """Backend-protocol adapter over a MicroBatchScheduler.

    generate() fans each prompt into its own queued request and blocks until
    every future resolves, so a strategy's per-round batched call becomes N
    coalescible units — two strategies running concurrently share engine
    batches instead of serializing whole runs. Token counting delegates
    straight to the real backend (host-side, thread-safe, no queue trip).

    A RequestShed on any prompt of a round propagates to the caller: the
    strategy run is aborted with the typed shed, matching the all-or-nothing
    semantics a deadline implies. ``records`` accumulates the per-request
    observability of every completed prompt for response-inline reporting.

    Streaming protocol (serve/gang.py): ``submit_round``/``harvest`` are
    the non-blocking half of generate() — a strategy that detects them
    submits a fan-out round and harvests completions as they land, so its
    reduce phase starts building while slow map children still decode
    instead of barriering on the whole round. Plain offline backends don't
    expose the pair, so strategies fall back to the barrier path there.
    """

    name = "queued"

    def __init__(self, scheduler: MicroBatchScheduler,
                 deadline: float | None = None,
                 trace: RequestTrace | None = None,
                 trace_id: str | None = None,
                 tenant: str = "", tier: str = "interactive",
                 gang: str = "") -> None:
        self.scheduler = scheduler
        self.deadline = deadline
        # ONE RequestTrace for the whole strategy run: every round's prompts
        # claim sub-tracks on it, so /debug/trace shows a summarize request
        # as one process with its map/collapse fan-out side by side
        self.trace = trace
        self.trace_id = trace_id
        # QoS class every fanned-out prompt inherits (serve/qos.py)
        self.tenant = tenant
        self.tier = tier
        # structured-job id every fanned-out prompt inherits (serve/gang.py);
        # "" = ungrouped (the raw /v1/generate path)
        self.gang_id = gang
        # streaming-summarize progress hook (serve/server.py): called with
        # the completed-prompt count after each round's completions land —
        # the SSE "progress" event source. None = no streaming
        self.progress = None
        self.records: list[ServeRequestRecord] = []  # guarded by: _lock
        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("serve.queued_backend")

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list[str]:
        if not prompts:
            return []
        # internal: this is the fan-out of an already-admitted request —
        # its admission happened at the entry point (check_admission), so a
        # wide strategy round must not shed itself against the depth budget
        # trace_owned: the entry point that built this view decided the
        # sampling — a trace=None here means "sampled out", not "re-draw"
        completions = self.scheduler.generate_sync(
            prompts, max_new_tokens=max_new_tokens, config=config,
            deadline=self.deadline, internal=True, references=references,
            cache_hints=cache_hints,
            trace=self.trace, trace_id=self.trace_id, trace_owned=True,
            tenant=self.tenant, tier=self.tier,
            # phase unlabeled: a barrier-mode generate() has no phase
            # knowledge (strategies that do label use submit_round)
            gang=self.gang_id,
        )
        if self.gang_id:
            self.scheduler.gangs.flush(self.gang_id)
        with self._lock:
            self.records.extend(c.record for c in completions)
            done = len(self.records)
        if self.progress is not None:
            self.progress(done)
        return [c.text for c in completions]

    # -- streaming fan-out (serve/gang.py) --------------------------------

    def submit_round(
        self,
        prompts: list[str],
        *,
        phase: str = "map",
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list:
        """Submit one fan-out round WITHOUT blocking: returns the futures
        aligned with ``prompts`` for ``harvest`` to drain in completion
        order. ``phase`` labels the members in the gang's journal record
        ("map" / "reduce" / "outline" / "expand") — the per-phase progress
        the poll surface reports. The gang's membership is flushed as one
        typed GANG record right after the round's admissions."""
        if not prompts:
            return []
        futs = self.scheduler.submit_many(
            prompts, references=references, cache_hints=cache_hints,
            max_new_tokens=max_new_tokens, config=config,
            deadline=self.deadline, internal=True,
            trace=self.trace, trace_id=self.trace_id, trace_owned=True,
            tenant=self.tenant, tier=self.tier,
            gang=self.gang_id, gang_phase=phase if self.gang_id else "",
        )
        if self.gang_id:
            self.scheduler.gangs.flush(self.gang_id)
        return futs

    def harvest(self, fut, *, tolerate_poison: bool = False) -> str | None:
        """Resolve ONE submit_round future: the text on success (progress
        fires per completion — the streaming client's per-child progress
        events), or None when ``tolerate_poison`` and the member failed
        typed POISON — the gang is marked ``partial`` (journaled) and the
        caller's reduce proceeds over the survivors. Every other failure
        (transient-out-of-budget, fatal, shed, cancelled) re-raises: a
        degraded summary is a poison-only contract, infrastructure
        failures still fail the request."""
        from .supervisor import FailureClass, RequestFailed

        try:
            # lint-allow[unbounded-blocking-wait]: externally bounded — same contract as generate_sync: every scheduler path resolves request futures (success, typed failure, shed, watchdog-resolved hangs)
            c = fut.result()
        except RequestFailed as e:
            if (
                tolerate_poison
                and self.gang_id
                and e.failure_class is FailureClass.POISON
            ):
                self.scheduler.gangs.mark_partial(self.gang_id)
                with self._lock:
                    done = len(self.records)
                if self.progress is not None:
                    self.progress(done)
                return None
            raise
        with self._lock:
            self.records.append(c.record)
            done = len(self.records)
        if self.progress is not None:
            self.progress(done)
        return c.text

    def count_tokens(self, text: str) -> int:
        return self.scheduler.backend.count_tokens(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return self.scheduler.backend.count_tokens_batch(texts)
