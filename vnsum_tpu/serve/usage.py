"""Per-tenant usage accounting behind a bounded-cardinality label registry.

PR 12 gave the server tenants (weights, quotas, tiers) but the obs layer
still answers per-tenant questions with two counters (requests, quota
sheds). Operators billing a multi-tenant service need the full ledger —
tokens in/out, cache savings, preemption/cancel/shed churn, and WINDOWED
latency per tenant (a tenant's p99 over the last minute, not since boot).
This module is that ledger, with one structural safeguard:

**Bounded cardinality.** Tenant names become Prometheus label values, and a
metric family's cost is its label cardinality — a caller cycling through
ten thousand tenant names (hostile or buggy) must not grow the scrape, the
ledger, or the registry without bound. :class:`TenantLabelRegistry` is the
ONE funnel every dynamically-labeled metric emission in serve/ routes
through (the ``metric-label-cardinality`` analysis rule enforces this
syntactically): it charset-sanitizes the name and caps the distinct names
tracked — the first ``cap`` names keep their own label, everything later
collapses into the ``other`` overflow label. Recency is tracked (LRU
order) so introspection shows who is active, but tracked names are never
evicted into ``other`` retroactively: a tenant's series never silently
merges after it has been reported.

Not internally locked: the owning `serve/metrics.ServeMetrics` serializes
every observation and snapshot under its one metrics lock, the same
contract as `obs/histogram.py` — per-tenant counts can therefore never
disagree with the aggregate counters they shipped with.
"""
from __future__ import annotations

import re
from collections import OrderedDict

from ..analysis.sanitizers import make_lock
from ..obs.histogram import (
    E2E_BUCKETS_S,
    TTFT_BUCKETS_S,
    WAIT_BUCKETS_S,
)
from ..obs.window import WindowedHistogram

# mirrors serve/qos.py's tenant-name charset: these names land verbatim in
# Prometheus label values, so quotes/backslashes/whitespace would corrupt
# the whole exposition
_NAME_RE = re.compile(r"[A-Za-z0-9_.-]+")

OTHER_LABEL = "other"
DEFAULT_TENANT = "default"


class TenantLabelRegistry:
    """Capped map from request-carried tenant names to metric label values.

    ``canonical(name)`` sanitizes and either returns the name (already
    tracked, or the cap has room) or :data:`OTHER_LABEL`. Declared tenants
    should be seeded at construction (``seed=``) so a table tenant can
    never lose its label to earlier hostile traffic.

    Self-locking, unlike the ledger: ``canonical`` is called both under
    the metrics lock (ledger observations) and bare at render time (label
    emission after the metrics snapshot is taken), so it carries its own
    innermost lock — it never acquires another serve lock while held.
    """

    # distinct-overflow tracking is itself bounded: past this many distinct
    # overflow names the `overflowed` gauge saturates ("at least N") —
    # the hostile-churn threat model must not buy memory through the very
    # counter that reports it
    OVERFLOW_TRACK_CAP = 4096

    def __init__(self, cap: int = 64, seed=None) -> None:
        self.cap = max(int(cap), 1)
        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("serve.labels")
        self._names: OrderedDict[str, None] = OrderedDict()  # guarded by: _lock
        self.overflowed = 0  # distinct names collapsed into "other" (saturating); monotone, racy reads fine
        self._overflow_seen: set[int] = set()                # guarded by: _lock
        for name in seed or ():
            self.track(name)

    def track(self, name: str) -> str:
        """Unconditionally reserve a label for a DECLARED tenant (the
        --tenants table). Operator config is bounded by definition, so
        seeding may grow past ``cap`` — otherwise past-the-cap declared
        tenants would all collapse into ``other`` and the per-tenant qos
        series would emit duplicate label sets (a whole-scrape reject).
        The cap guards dynamic, request-carried names only."""
        name = self.sanitize(name)
        if name == OTHER_LABEL:
            return OTHER_LABEL
        with self._lock:
            if name not in self._names:
                self._names[name] = None
        return name

    @staticmethod
    def sanitize(name: str) -> str:
        if name and _NAME_RE.fullmatch(name):
            return name
        cleaned = re.sub(r"[^A-Za-z0-9_.-]", "_", name or "")
        return cleaned or DEFAULT_TENANT

    def canonical(self, name: str, touch: bool = True) -> str:
        """The metric-safe label for ``name`` — THE helper the
        metric-label-cardinality lint requires on every dynamic label.
        Idempotent: the overflow label itself canonicalizes to itself
        without counting as an overflowed tenant (render paths re-feed
        ledger keys that are already canonical). ``touch=False`` is the
        read-path form: scrape-time emission must not rewrite the LRU
        recency that observation-path traffic established."""
        name = self.sanitize(name)
        if name == OTHER_LABEL:
            return OTHER_LABEL
        with self._lock:
            if name in self._names:
                if touch:
                    self._names.move_to_end(name)  # recency: who is active
                return name
            if len(self._names) < self.cap:
                self._names[name] = None
                return name
            # cap reached: the overflow label absorbs every new name.
            # Distinct-name counting is bounded too (OVERFLOW_TRACK_CAP
            # hashes, then the gauge saturates)
            h = hash(name)
            if (
                h not in self._overflow_seen
                and len(self._overflow_seen) < self.OVERFLOW_TRACK_CAP
            ):
                self._overflow_seen.add(h)
                self.overflowed += 1
            return OTHER_LABEL

    def tracked(self) -> list[str]:
        """Tracked names, least-recently-used first."""
        with self._lock:
            return list(self._names)


class TenantUsage:
    """One tenant's ledger row: monotone counters + windowed latency."""

    __slots__ = (
        "requests", "completed", "errors", "sheds", "cancels",
        "preemptions", "requeues", "prompt_tokens", "generated_tokens",
        "cached_tokens", "queue_wait", "ttft", "e2e",
    )

    def __init__(self, horizon_s: float, sub_windows: int, clock) -> None:
        self.requests = 0
        self.completed = 0
        self.errors = 0
        self.sheds = 0
        self.cancels = 0
        self.preemptions = 0
        self.requeues = 0
        self.prompt_tokens = 0
        self.generated_tokens = 0
        self.cached_tokens = 0
        kw = dict(horizon_s=horizon_s, sub_windows=sub_windows, clock=clock)
        self.queue_wait = WindowedHistogram(WAIT_BUCKETS_S, **kw)
        self.ttft = WindowedHistogram(TTFT_BUCKETS_S, **kw)
        self.e2e = WindowedHistogram(E2E_BUCKETS_S, **kw)


class UsageLedger:
    """All tenants' usage rows, keyed by the registry's canonical labels so
    the ledger itself is as bounded as the scrape."""

    def __init__(self, registry: TenantLabelRegistry | None = None,
                 horizon_s: float = 600.0, sub_windows: int = 60,
                 clock=None) -> None:
        import time

        self.registry = registry or TenantLabelRegistry()
        self.horizon_s = float(horizon_s)
        self.sub_windows = int(sub_windows)
        self._clock = clock or time.monotonic
        self._tenants: dict[str, TenantUsage] = {}

    def row(self, tenant: str) -> TenantUsage:
        key = self.registry.canonical(tenant or DEFAULT_TENANT)
        row = self._tenants.get(key)
        if row is None:
            row = TenantUsage(self.horizon_s, self.sub_windows, self._clock)
            self._tenants[key] = row
        return row

    # -- observation hooks (called by ServeMetrics under ITS lock) --------

    def observe_submit(self, tenant: str, n: int = 1) -> None:
        self.row(tenant).requests += n

    def observe_shed(self, tenant: str, n: int = 1) -> None:
        self.row(tenant).sheds += n

    def observe_cancel(self, tenant: str, n: int = 1) -> None:
        self.row(tenant).cancels += n

    def observe_preemption(self, tenant: str, n: int = 1) -> None:
        self.row(tenant).preemptions += n

    def observe_requeue(self, tenant: str, n: int = 1) -> None:
        self.row(tenant).requeues += n

    def observe_request(self, tenant: str, rec) -> None:
        """One terminal ServeRequestRecord: tokens, outcome, and the
        windowed latency observations (TTFT only when anchored — the same
        honesty rule the aggregate histogram applies)."""
        row = self.row(tenant)
        row.prompt_tokens += rec.prompt_tokens
        row.generated_tokens += rec.generated_tokens
        row.cached_tokens += rec.cached_prompt_tokens
        row.queue_wait.observe(rec.queue_wait_s, exemplar=rec.trace_id)
        if rec.status == "ok":
            row.completed += 1
            if rec.ttft_anchored:
                row.ttft.observe(rec.ttft_s, exemplar=rec.trace_id)
            row.e2e.observe(rec.total_s, exemplar=rec.trace_id)
        elif rec.status == "error":
            row.errors += 1

    # -- export ------------------------------------------------------------

    def tenants(self) -> list[str]:
        return sorted(self._tenants)

    def snapshot(self, window_s: float | None = None) -> dict:
        """{tenant: {counters..., latency quantiles over ``window_s``}} —
        the `GET /v1/usage` payload and the bench's usage evidence. ONE
        ``now`` for the whole snapshot, so a sub-window boundary crossed
        mid-iteration cannot skew tenants (or metrics within a tenant)
        against each other."""
        now = self._clock()
        out = {}
        for name in self.tenants():
            row = self._tenants[name]
            entry = {
                "requests": row.requests,
                "completed": row.completed,
                "errors": row.errors,
                "sheds": row.sheds,
                "cancels": row.cancels,
                "preemptions": row.preemptions,
                "requeues": row.requeues,
                "prompt_tokens": row.prompt_tokens,
                "generated_tokens": row.generated_tokens,
                "cached_tokens_saved": row.cached_tokens,
            }
            for key, wh in (("queue_wait", row.queue_wait),
                            ("ttft", row.ttft), ("e2e", row.e2e)):
                h = wh.merged(window_s, now)
                entry[key] = {
                    "count": h.count,
                    "p50_s": round(h.percentile(0.50), 6),
                    "p95_s": round(h.percentile(0.95), 6),
                    "p99_s": round(h.percentile(0.99), 6),
                }
            out[name] = entry
        return out
