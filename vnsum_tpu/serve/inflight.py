"""In-flight scheduler: slot-feeding over a persistent engine decode loop.

`MicroBatchScheduler` is batch-dispatch: coalesce, call a blocking
``backend.generate``, repeat — every request that arrives mid-batch waits
out the full prefill+decode of strangers. This scheduler replaces the
dispatch loop with *slot feeding* over the backend's in-flight slot loop
(``backend.start_slot_loop``, Orca-style iteration-level scheduling): one
long-lived fixed-shape decode batch where, at every segment boundary,
finished rows are harvested and freed slots are refilled straight from the
queue (``RequestQueue.take_upto`` — admission billed per slot). Joiners get
their own chunked prefill (optionally resumed from the radix prefix cache),
so per-request TTFT is anchored at the JOINER's prefill end — not at a
shared batch's — and a request's time-to-first-token no longer includes
strangers' decode.

Policy notes:

- **compatibility**: a loop serves ONE batch key (max_new_tokens +
  GenerationConfig — the same coalescing rule as batch dispatch). Requests
  with other keys wait; compatible later arrivals may leapfrog them into
  free slots, but an incompatible head-of-line older than
  ``switch_grace_s`` stops refills so the loop drains and is rebuilt for
  the new key (bounded unfairness instead of starvation).
- **oversized prompts**: prompts beyond the loop's prompt bucket are
  rejected at admit and served through the classic batch-dispatch path
  (``_run_batch``) between segments — the offline one-shot program remains
  the path of record for them.
- **speculation**: the slot loop has no spec-decode variant; references are
  ignored in in-flight mode (greedy outputs are identical either way).

Everything else — submission, admission control, deadline shedding,
QueuedBackend strategy fan-out, metrics surfaces — is inherited from
MicroBatchScheduler; only the engine-side loop differs.
"""
from __future__ import annotations

import time

from ..backend.base import Backend
from ..core.logging import get_logger
from ..core.results import ServeRequestRecord
from .queue import RequestShed, ServeRequest, ShedReason
from .scheduler import MicroBatchScheduler, _Completion

logger = get_logger("vnsum.serve.inflight")


class InflightScheduler(MicroBatchScheduler):
    def __init__(
        self,
        backend: Backend,
        *,
        slots: int | None = None,
        slot_prompt_tokens: int = 0,
        switch_grace_s: float = 0.5,
        **kw,
    ) -> None:
        if not callable(getattr(backend, "start_slot_loop", None)):
            raise ValueError(
                f"backend {getattr(backend, 'name', backend)!r} does not "
                "expose start_slot_loop; use MicroBatchScheduler"
            )
        # set before super().__init__ — the base constructor starts the
        # scheduler thread, which reads these immediately
        self.slots = slots or kw.get("max_batch", 8)
        self.slot_prompt_tokens = slot_prompt_tokens
        self.switch_grace_s = switch_grace_s
        # live loop reference for scrape-time gauges (written only by the
        # scheduler thread; racy reads yield a stale gauge, never a crash)
        self._live_loop = None
        super().__init__(backend, **kw)

    # -- scrape surface ---------------------------------------------------

    def slot_state(self) -> tuple[int, int] | None:
        """(slots_total, slots_busy) for /metrics, or None when no loop is
        resident yet."""
        loop = self._live_loop
        if loop is None:
            return (self.slots, 0)
        return (loop.slots, loop.active)

    # -- scheduler thread -------------------------------------------------

    def _loop(self) -> None:
        loop = None
        loop_key = None
        pending: list[ServeRequest] = []
        draining = False  # queue closed: serve what remains, then exit
        while True:
            try:
                active = loop.active if loop is not None else 0
                if not draining and not pending:
                    taken = self._take(loop, loop_key, active)
                    if taken is None:
                        draining = True
                    else:
                        pending.extend(taken)
                if draining and not pending and not active:
                    self._close_loop(loop)
                    return
                if pending and not active:
                    key = pending[0].batch_key()
                    if loop is None or key != loop_key:
                        self._close_loop(loop)
                        loop = self._make_loop(pending[0])
                        loop_key = key
                if (
                    pending
                    and loop is not None
                    and pending[0].batch_key() == loop_key
                    and loop.free
                ):
                    pending = self._admit(loop, pending)
                if loop is not None and loop.active:
                    self._run_segment(loop)
            except Exception as e:  # pragma: no cover - belt and braces
                # a loop failure must not kill serving: fail every resident
                # and pending future with the error — recorded in metrics
                # and traces like the base scheduler's errored batches —
                # drop the loop, and keep taking new work on a fresh one
                logger.exception("in-flight loop failed; rebuilding")
                now = time.monotonic()
                for r in self._evict_all(loop, pending):
                    adm = getattr(r, "inflight_admission", None)
                    t0 = adm.admitted_at if adm is not None else now
                    rec = ServeRequestRecord(
                        request_id=r.request_id, status="error",
                        trace_id=r.trace_id,
                        queue_wait_s=max(t0 - r.enqueued_at, 0.0),
                        engine_s=max(now - t0, 0.0),
                        total_s=max(now - r.enqueued_at, 0.0),
                        prompt_tokens=r.est_tokens,
                    )
                    self.metrics.observe_request(rec)
                    self._trace_request(r, t0, max(now - t0, 0.0), None,
                                        "error")
                    if not r.future.done():
                        r.future.set_exception(e)
                loop, loop_key, pending = None, None, []

    def _take(self, loop, loop_key, active: int):
        """One queue interaction: blocking for the head when idle,
        non-blocking slot-feeding when decoding."""
        if not active:
            return self.queue.take_upto(
                self.slots, wait_s=max(self.max_wait_s, 0.05)
            )
        if loop is None or not loop.free:
            return []
        head = self.queue.head_snapshot()
        if (
            head is not None
            and head[0] != loop_key
            and time.monotonic() - head[1] > self.switch_grace_s
        ):
            # an incompatible head has waited long enough: stop refilling
            # so the resident batch drains and the loop is rebuilt for it
            return []
        return self.queue.take_upto(loop.free, key=loop_key)

    def _make_loop(self, head: ServeRequest):
        loop = self.backend.start_slot_loop(
            self.slots,
            max_new_tokens=head.max_new_tokens,
            config=head.config,
            prompt_tokens=self.slot_prompt_tokens,
        )
        self._live_loop = loop
        return loop

    def _close_loop(self, loop) -> None:
        if loop is not None:
            self._live_loop = None
            loop.close()

    def _evict_all(self, loop, pending: list[ServeRequest]):
        """Collect every request still owed an answer after a loop failure."""
        stranded = list(pending)
        if loop is not None:
            stranded.extend(loop.outstanding())
            self._close_loop(loop)
        self._live_loop = None
        return stranded

    # -- admission ---------------------------------------------------------

    def _admit(self, loop, pending: list[ServeRequest]) -> list[ServeRequest]:
        now = time.monotonic()
        live: list[ServeRequest] = []
        for r in pending:
            if r.expired(now):
                # the queue sheds expired requests it still holds; taken-but
                # -unadmitted ones are this scheduler's to shed — including
                # the owned-trace finalization the queue-side _on_shed hook
                # performs, so SLO-miss requests still reach /debug/trace
                self.metrics.observe_shed(ShedReason.DEADLINE)
                if r.own_trace and r.trace is not None and self.obs is not None:
                    self.obs.finish_request(r.trace, "shed:deadline")
                    r.trace = None
                if not r.future.done():
                    r.future.set_exception(RequestShed(ShedReason.DEADLINE))
            else:
                live.append(r)
        pending = live
        if not pending or not loop.free:
            return pending
        was_running = loop.active > 0
        items = [(r, r.prompt, r.cache_hint) for r in pending[: loop.free]]
        admissions, rejected = loop.admit(items)
        admitted_ids = {id(a.key) for a in admissions}
        rejected_ids = {id(k) for k in rejected}
        for adm in admissions:
            r: ServeRequest = adm.key
            r.inflight_admission = adm  # read back at harvest
        if admissions:
            prefill_s = admissions[0].prefill_end - admissions[0].admitted_at
            self.metrics.observe_batch(len(admissions), prefill_s)
            if was_running:
                self.metrics.observe_refill(len(admissions))
        if rejected:
            # prompts beyond the loop's S bucket: classic batch dispatch
            # between segments (residents wait one blocking generate —
            # bounded by the oversized request itself, and the one-shot
            # program stays the path of record for it)
            fallback = [r for r in pending if id(r) in rejected_ids]
            logger.info(
                "dispatching %d oversized request(s) via the one-shot path",
                len(fallback),
            )
            self._run_batch(fallback)
        return [
            r for r in pending
            if id(r) not in admitted_ids and id(r) not in rejected_ids
        ]

    # -- segment + harvest --------------------------------------------------

    def _run_segment(self, loop) -> None:
        res = loop.step()
        self.metrics.observe_segment(res.live, res.seconds, res.new_tokens)
        now = time.monotonic()
        for c in res.completions:
            r: ServeRequest = c.key
            adm = getattr(r, "inflight_admission", None)
            t_admit = adm.admitted_at if adm is not None else now
            engine_s = now - t_admit
            rec = ServeRequestRecord(
                request_id=r.request_id,
                status="ok",
                trace_id=r.trace_id,
                queue_wait_s=max(t_admit - r.enqueued_at, 0.0),
                engine_s=engine_s,
                total_s=max(now - r.enqueued_at, 0.0),
                # TTFT anchored at the JOINER's own prefill end — the whole
                # point of refill: first-token time no longer includes
                # strangers' decode
                ttft_s=max(
                    (adm.prefill_end if adm is not None else now)
                    - r.enqueued_at, 0.0,
                ),
                ttft_anchored=adm is not None,
                batch_size=adm.occupancy if adm is not None else res.live,
                prompt_tokens=r.est_tokens,
                generated_tokens=c.gen_tokens,
            )
            rec.cached_prompt_tokens = (
                adm.cached_tokens if adm is not None else 0
            )
            self.metrics.observe_request(rec)
            self._trace_request(r, t_admit, engine_s, None, "ok")
            if not r.future.done():
                r.future.set_result(_Completion(c.text, rec))
