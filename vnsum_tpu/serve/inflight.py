"""In-flight scheduler: slot-feeding over a persistent engine decode loop.

`MicroBatchScheduler` is batch-dispatch: coalesce, call a blocking
``backend.generate``, repeat — every request that arrives mid-batch waits
out the full prefill+decode of strangers. This scheduler replaces the
dispatch loop with *slot feeding* over the backend's in-flight slot loop
(``backend.start_slot_loop``, Orca-style iteration-level scheduling): one
long-lived fixed-shape decode batch where, at every segment boundary,
finished rows are harvested and freed slots are refilled straight from the
queue (``RequestQueue.take_upto`` — admission billed per slot). Joiners get
their own chunked prefill (optionally resumed from the radix prefix cache),
so per-request TTFT is anchored at the JOINER's prefill end — not at a
shared batch's — and a request's time-to-first-token no longer includes
strangers' decode.

Policy notes:

- **compatibility**: a loop serves ONE batch key (max_new_tokens +
  GenerationConfig — the same coalescing rule as batch dispatch). Requests
  with other keys wait; compatible later arrivals may leapfrog them into
  free slots, but an incompatible head-of-line older than
  ``switch_grace_s`` stops refills so the loop drains and is rebuilt for
  the new key (bounded unfairness instead of starvation).
- **oversized prompts**: prompts beyond the loop's prompt bucket are
  rejected at admit and served through the classic batch-dispatch path
  (``_run_batch``) between segments — the offline one-shot program remains
  the path of record for them.
- **speculation**: the slot loop has no spec-decode variant; references are
  ignored in in-flight mode (greedy outputs are identical either way).
- **fault tolerance**: a loop crash (admit or segment) evicts every
  resident — slots freed, radix pins released by the loop's own finally
  paths — and, when a supervisor is configured, re-runs stranded requests
  through the SUPERVISED one-shot dispatch path grouped by batch key, so
  retry/bisect/poison-quarantine are inherited rather than re-implemented;
  the rebuilt loop then serves new work. Without a supervisor every
  stranded future fails with the raw error (legacy contract).

Everything else — submission, admission control, deadline shedding,
QueuedBackend strategy fan-out, metrics surfaces — is inherited from
MicroBatchScheduler; only the engine-side loop differs.
"""
from __future__ import annotations

import time

from ..backend.base import Backend
from ..core.logging import get_logger
from ..core.results import ServeRequestRecord
from .queue import ServeRequest, ShedReason
from .scheduler import MicroBatchScheduler, _Completion

logger = get_logger("vnsum.serve.inflight")


class InflightScheduler(MicroBatchScheduler):
    def __init__(
        self,
        backend: Backend,
        *,
        slots: int | None = None,
        slot_prompt_tokens: int = 0,
        switch_grace_s: float = 0.5,
        **kw,
    ) -> None:
        if not callable(getattr(backend, "start_slot_loop", None)):
            raise ValueError(
                f"backend {getattr(backend, 'name', backend)!r} does not "
                "expose start_slot_loop; use MicroBatchScheduler"
            )
        # set before super().__init__ — the base constructor starts the
        # scheduler thread, which reads these immediately
        self.slots = slots or kw.get("max_batch", 8)
        self.slot_prompt_tokens = slot_prompt_tokens
        self.switch_grace_s = switch_grace_s
        # live loop reference for scrape-time gauges (written only by the
        # scheduler thread; racy reads yield a stale gauge, never a crash)
        self._live_loop = None
        # taken-but-not-yet-admitted requests (scheduler-thread state; an
        # instance attribute so close() can shed them on drain overrun)
        self._pending: list[ServeRequest] = []
        super().__init__(backend, **kw)

    # -- scrape surface ---------------------------------------------------

    def slot_state(self) -> tuple[int, int] | None:
        """(slots_total, slots_busy) for /metrics, or None when no loop is
        resident yet."""
        loop = self._live_loop
        if loop is None:
            return (self.slots, 0)
        return (loop.slots, loop.active)

    # -- scheduler thread -------------------------------------------------

    def _take_limit(self) -> int:
        """Slot budget under the degradation ladder: a rebuilt loop at
        REDUCED_BATCH or below runs half the slots (a resident full-size
        loop keeps its shape — shrinking applies at the next rebuild)."""
        if self.supervisor is not None:
            return self.supervisor.batch_limit(self.slots)
        return self.slots

    def _loop(self) -> None:
        loop = None
        loop_key = None
        self._pending = []
        draining = False  # queue closed: serve what remains, then exit
        while True:
            try:
                active = loop.active if loop is not None else 0
                if not draining and not self._pending:
                    taken = self._take(loop, loop_key, active)
                    if taken is None:
                        draining = True
                    else:
                        self._pending.extend(taken)
                if draining and not self._pending and not active:
                    self._close_loop(loop)
                    return
                if self._pending and not active:
                    key = self._pending[0].batch_key()
                    if loop is None or key != loop_key:
                        self._close_loop(loop)
                        loop = self._make_loop(self._pending[0])
                        loop_key = key
                if (
                    self._pending
                    and loop is not None
                    and self._pending[0].batch_key() == loop_key
                    and loop.free
                ):
                    self._pending = self._admit(loop, self._pending)
                if loop is not None and loop.active:
                    self._run_segment(loop)
                    if self.supervisor is not None:
                        self.supervisor.record_success()
                        self._apply_rung()
            except Exception as e:  # exercised by tests/test_serve_faults.py
                # a loop failure must not kill serving: every resident and
                # pending request is evicted (slots freed, radix pins
                # released by the loop's own finally paths) and resolved —
                # retried through the supervised one-shot path when a
                # supervisor is configured, failed with the raw error
                # otherwise — then the loop is rebuilt for new work
                logger.exception("in-flight loop failed; recovering")
                stranded = self._evict_all(loop, self._pending)
                loop, loop_key = None, None
                self._pending = []
                self._resolve_loop_failure(stranded, e)

    def _resolve_loop_failure(self, stranded: list[ServeRequest],
                              e: Exception) -> None:
        """Resolve every request owed an answer after a slot-loop crash.

        Supervised: the crash is classified and noted (ladder strikes
        included), then survivors are re-run through the SUPERVISED one-shot
        dispatch path (``_run_batch``) grouped by batch key — the slot
        loop's per-request decode state died with it, and the one-shot
        program recomputes from scratch, so retry/bisect/quarantine and
        "every future resolves" are inherited rather than re-implemented.
        Unsupervised: the legacy contract — every stranded future fails
        with the raw error."""
        from .supervisor import FailureClass

        sup = self.supervisor
        if sup is not None:
            cls = sup.classify(e)
            self.metrics.observe_failure(cls.value)
            sup.note_failure(cls)
            self._apply_rung()
            if not stranded:
                return
            if cls is FailureClass.FATAL:
                self._attempt_ctx = (time.monotonic(), 0.0, None)
                self._resolve_failed(stranded, e, cls)
                return
            delay = sup.backoff_s(1)
            self.metrics.observe_retry(len(stranded))
            self.metrics.observe_backoff(delay)
            for r in stranded:
                self._trace_fault(r, "retry", cls.value, delay)
            logger.warning(
                "retrying %d stranded request(s) via the one-shot path "
                "after %s loop failure (backoff %.3fs)",
                len(stranded), cls.value, delay,
            )
            time.sleep(delay)
            # group by batch key: residents share the dead loop's key, but
            # pending may already carry the NEXT key awaiting a loop switch
            # — mixing them in one generate would apply the head's params
            # to everyone
            groups: dict[tuple, list[ServeRequest]] = {}
            for r in stranded:
                groups.setdefault(r.batch_key(), []).append(r)
            for group in groups.values():
                self._run_batch(group)
            return
        now = time.monotonic()
        for r in stranded:
            adm = getattr(r, "inflight_admission", None)
            t0 = adm.admitted_at if adm is not None else now
            rec = ServeRequestRecord(
                request_id=r.request_id, status="error",
                trace_id=r.trace_id,
                queue_wait_s=max(t0 - r.enqueued_at, 0.0),
                engine_s=max(now - t0, 0.0),
                total_s=max(now - r.enqueued_at, 0.0),
                prompt_tokens=r.est_tokens,
            )
            self.metrics.observe_request(rec)
            self._trace_request(r, t0, max(now - t0, 0.0), None, "error")
            self._journal_fail(r, "error", str(e))
            if not r.future.done():
                r.future.set_exception(e)

    def _stranded_snapshot(self) -> list[ServeRequest]:
        stranded = list(self._pending)
        loop = self._live_loop
        if loop is not None:
            stranded.extend(loop.outstanding())
        return stranded

    def _take(self, loop, loop_key, active: int):
        """One queue interaction: blocking for the head when idle,
        non-blocking slot-feeding when decoding."""
        if not active:
            return self.queue.take_upto(
                self._take_limit(), wait_s=max(self.max_wait_s, 0.05)
            )
        if loop is None or not loop.free:
            return []
        head = self.queue.head_snapshot()
        if (
            head is not None
            and head[0] != loop_key
            and time.monotonic() - head[1] > self.switch_grace_s
        ):
            # an incompatible head has waited long enough: stop refilling
            # so the resident batch drains and the loop is rebuilt for it
            return []
        return self.queue.take_upto(loop.free, key=loop_key)

    def _make_loop(self, head: ServeRequest):
        loop = self.backend.start_slot_loop(
            self._take_limit(),
            max_new_tokens=head.max_new_tokens,
            config=head.config,
            prompt_tokens=self.slot_prompt_tokens,
        )
        self._live_loop = loop
        return loop

    def _close_loop(self, loop) -> None:
        if loop is not None:
            self._live_loop = None
            loop.close()

    def _evict_all(self, loop, pending: list[ServeRequest]):
        """Collect every request still owed an answer after a loop failure."""
        stranded = list(pending)
        if loop is not None:
            stranded.extend(loop.outstanding())
            self._close_loop(loop)
        self._live_loop = None
        return stranded

    # -- admission ---------------------------------------------------------

    def _admit(self, loop, pending: list[ServeRequest]) -> list[ServeRequest]:
        now = time.monotonic()
        live: list[ServeRequest] = []
        for r in pending:
            if r.expired(now):
                # the queue sheds expired requests it still holds; taken-but
                # -unadmitted ones are this scheduler's to shed — including
                # the owned-trace finalization the queue-side _on_shed hook
                # performs, so SLO-miss requests still reach /debug/trace
                self._shed_taken(r, ShedReason.DEADLINE)
            else:
                live.append(r)
        pending = live
        if not pending or not loop.free:
            return pending
        was_running = loop.active > 0
        items = [(r, r.prompt, r.cache_hint) for r in pending[: loop.free]]
        admissions, rejected = loop.admit(items)
        admitted_ids = {id(a.key) for a in admissions}
        rejected_ids = {id(k) for k in rejected}
        for adm in admissions:
            r: ServeRequest = adm.key
            r.inflight_admission = adm  # read back at harvest
            if self.journal is not None and r.journal_rid is not None:
                # slot admission IS this request's engine start: its own
                # prefill ran (the one-shot path journals START per batch
                # dispatch in _dispatch instead)
                self.journal.start(r.journal_rid)
        if admissions:
            prefill_s = admissions[0].prefill_end - admissions[0].admitted_at
            self.metrics.observe_batch(len(admissions), prefill_s)
            if was_running:
                self.metrics.observe_refill(len(admissions))
        if rejected:
            # prompts beyond the loop's S bucket: classic batch dispatch
            # between segments (residents wait one blocking generate —
            # bounded by the oversized request itself, and the one-shot
            # program stays the path of record for it)
            fallback = [r for r in pending if id(r) in rejected_ids]
            logger.info(
                "dispatching %d oversized request(s) via the one-shot path",
                len(fallback),
            )
            self._run_batch(fallback)
        return [
            r for r in pending
            if id(r) not in admitted_ids and id(r) not in rejected_ids
        ]

    # -- segment + harvest --------------------------------------------------

    def _run_segment(self, loop) -> None:
        res = loop.step()
        self.metrics.observe_segment(res.live, res.seconds, res.new_tokens)
        now = time.monotonic()
        for c in res.completions:
            r: ServeRequest = c.key
            adm = getattr(r, "inflight_admission", None)
            t_admit = adm.admitted_at if adm is not None else now
            engine_s = now - t_admit
            rec = ServeRequestRecord(
                request_id=r.request_id,
                status="ok",
                trace_id=r.trace_id,
                queue_wait_s=max(t_admit - r.enqueued_at, 0.0),
                engine_s=engine_s,
                total_s=max(now - r.enqueued_at, 0.0),
                # TTFT anchored at the JOINER's own prefill end — the whole
                # point of refill: first-token time no longer includes
                # strangers' decode
                ttft_s=max(
                    (adm.prefill_end if adm is not None else now)
                    - r.enqueued_at, 0.0,
                ),
                ttft_anchored=adm is not None,
                batch_size=adm.occupancy if adm is not None else res.live,
                prompt_tokens=r.est_tokens,
                generated_tokens=c.gen_tokens,
            )
            rec.cached_prompt_tokens = (
                adm.cached_tokens if adm is not None else 0
            )
            self.metrics.observe_request(rec)
            self._trace_request(r, t_admit, engine_s, None, "ok")
            if self.journal is not None and r.journal_rid is not None:
                # ledger before future, same ordering rationale as the
                # one-shot path in scheduler._dispatch
                self.journal.complete(r.journal_rid, c.text, c.gen_tokens)
            if not r.future.done():
                r.future.set_result(_Completion(c.text, rec))
