"""In-flight scheduler: slot-feeding over a persistent engine decode loop.

`MicroBatchScheduler` is batch-dispatch: coalesce, call a blocking
``backend.generate``, repeat — every request that arrives mid-batch waits
out the full prefill+decode of strangers. This scheduler replaces the
dispatch loop with *slot feeding* over the backend's in-flight slot loop
(``backend.start_slot_loop``, Orca-style iteration-level scheduling): one
long-lived fixed-shape decode batch where, at every segment boundary,
finished rows are harvested and freed slots are refilled straight from the
queue (``RequestQueue.take_upto`` — admission billed per slot). Joiners get
their own chunked prefill (optionally resumed from the radix prefix cache),
so per-request TTFT is anchored at the JOINER's prefill end — not at a
shared batch's — and a request's time-to-first-token no longer includes
strangers' decode.

Policy notes:

- **compatibility**: a loop serves ONE batch key (max_new_tokens +
  GenerationConfig — the same coalescing rule as batch dispatch). Requests
  with other keys wait; compatible later arrivals may leapfrog them into
  free slots, but an incompatible head-of-line older than
  ``switch_grace_s`` stops refills so the loop drains and is rebuilt for
  the new key (bounded unfairness instead of starvation).
- **oversized prompts**: prompts beyond the loop's prompt bucket are
  rejected at admit and served through the classic batch-dispatch path
  (``_run_batch``) between segments — the offline one-shot program remains
  the path of record for them.
- **speculation**: the slot loop has no spec-decode variant; references are
  ignored in in-flight mode (greedy outputs are identical either way).
- **fault tolerance**: a loop crash (admit or segment) evicts every
  resident — slots freed, radix pins released by the loop's own finally
  paths — and, when a supervisor is configured, re-runs stranded requests
  through the SUPERVISED one-shot dispatch path grouped by batch key, so
  retry/bisect/poison-quarantine are inherited rather than re-implemented;
  the rebuilt loop then serves new work. Without a supervisor every
  stranded future fails with the raw error (legacy contract).

Everything else — submission, admission control, deadline shedding,
QueuedBackend strategy fan-out, metrics surfaces — is inherited from
MicroBatchScheduler; only the engine-side loop differs.
"""
from __future__ import annotations

import os
import time

from ..backend.base import Backend
from ..core.logging import get_logger
from ..core.results import ServeRequestRecord
from .queue import ServeRequest, ShedReason
from .scheduler import MicroBatchScheduler, _Completion

logger = get_logger("vnsum.serve.inflight")


class InflightScheduler(MicroBatchScheduler):
    def __init__(
        self,
        backend: Backend,
        *,
        slots: int | None = None,
        slot_prompt_tokens: int = 0,
        switch_grace_s: float = 0.5,
        preempt_budget: int = 16,
        fused_segments: int = 1,
        **kw,
    ) -> None:
        if not callable(getattr(backend, "start_slot_loop", None)):
            raise ValueError(
                f"backend {getattr(backend, 'name', backend)!r} does not "
                "expose start_slot_loop; use MicroBatchScheduler"
            )
        # set before super().__init__ — the base constructor starts the
        # scheduler thread, which reads these immediately
        self.slots = slots or kw.get("max_batch", 8)
        self.slot_prompt_tokens = slot_prompt_tokens
        self.switch_grace_s = switch_grace_s
        # fused multi-step decode: the loop dispatches N on-device segments
        # per host round-trip, so joins, cancel/preempt polls, and stream
        # deltas run at the FUSED cadence — the TTFT/goodput trade knob
        # (--fused-segments; bench_serving.py's fused phase sweeps it)
        self.fused_segments = max(int(fused_segments), 1)
        # preemption cap per request: a batch-tier request evicted this
        # many times becomes non-evictable — bounded interference instead
        # of starvation-by-interactive-pressure (it keeps its slot from
        # then on and finishes)
        self.preempt_budget = max(int(preempt_budget), 1)
        # chaos-soak kill window (scripts/chaos_soak.py): sleep this long
        # between slot eviction and the PREEMPTED journal append so an
        # out-of-process SIGKILL can land exactly in the gap the ledger
        # invariant must survive. 0 (the default) adds nothing
        self._preempt_gap_s = (
            float(os.environ.get("VNSUM_CHAOS_PREEMPT_GAP_MS", "0")) / 1000.0
        )
        # live loop reference for scrape-time gauges (written only by the
        # scheduler thread; racy reads yield a stale gauge, never a crash)
        self._live_loop = None
        # taken-but-not-yet-admitted requests (scheduler-thread state; an
        # instance attribute so close() can shed them on drain overrun)
        self._pending: list[ServeRequest] = []
        super().__init__(backend, **kw)

    # -- scrape surface ---------------------------------------------------

    def slot_state(self) -> tuple[int, int] | None:
        """(slots_total, slots_busy) for /metrics, or None when no loop is
        resident yet."""
        loop = self._live_loop
        if loop is None:
            return (self.slots, 0)
        return (loop.slots, loop.active)

    # -- scheduler thread -------------------------------------------------

    def _take_limit(self) -> int:
        """Slot budget under the degradation ladder: a rebuilt loop at
        REDUCED_BATCH or below runs half the slots (a resident full-size
        loop keeps its shape — shrinking applies at the next rebuild)."""
        if self.supervisor is not None:
            return self.supervisor.batch_limit(self.slots)
        return self.slots

    def _loop(self) -> None:
        loop = None
        loop_key = None
        self._pending = []
        draining = False  # queue closed: serve what remains, then exit
        while True:
            if self._stale_thread():
                return  # replaced by watchdog recovery; the successor runs
            if self._hb is not None:
                self._hb.beat()
            try:
                self._cancel_sweep_inflight(loop)
                if not draining and self.tenants is not None:
                    self._maybe_preempt(loop, loop_key)
                active = loop.active if loop is not None else 0
                if not draining and not self._pending:
                    taken = self._take(loop, loop_key, active)
                    if taken is None:
                        draining = True
                    else:
                        self._pending.extend(taken)
                if draining and not self._pending and not active:
                    self._close_loop(loop)
                    if self.watchdog is not None and not self._stale_thread():
                        self.watchdog.unregister("scheduler")
                    return
                if self._pending and not active:
                    key = self._pending[0].batch_key()
                    if loop is None or key != loop_key:
                        self._close_loop(loop)
                        loop = self._make_loop(self._pending[0])
                        loop_key = key
                if (
                    self._pending
                    and loop is not None
                    and self._pending[0].batch_key() == loop_key
                    and loop.free
                ):
                    admitted = self._admit(loop, self._pending)
                    if self._stale_thread():
                        # hung admit: the successor owns _pending now — an
                        # assignment here would clobber its taken work
                        return
                    self._pending = admitted
                if loop is not None and loop.active:
                    self._run_segment(loop)
                    if self._stale_thread():
                        # hung segment: a late record_success here would
                        # clear the very strike the recovery just charged
                        return
                    if self.supervisor is not None:
                        self.supervisor.record_success()
                        self._apply_rung()
            except Exception as e:  # exercised by tests/test_serve_faults.py
                if self._stale_thread():
                    # a late error out of a loop the watchdog already tore
                    # down and requeued: the successor owns everything now
                    return
                # a loop failure must not kill serving: every resident and
                # pending request is evicted (slots freed, radix pins
                # released by the loop's own finally paths) and resolved —
                # retried through the supervised one-shot path when a
                # supervisor is configured, failed with the raw error
                # otherwise — then the loop is rebuilt for new work
                logger.exception("in-flight loop failed; recovering")
                stranded = self._evict_all(loop, self._pending)
                loop, loop_key = None, None
                self._pending = []
                self._resolve_loop_failure(stranded, e)

    def _resolve_loop_failure(self, stranded: list[ServeRequest],
                              e: Exception) -> None:
        """Resolve every request owed an answer after a slot-loop crash.

        Supervised: the crash is classified and noted (ladder strikes
        included), then survivors are re-run through the SUPERVISED one-shot
        dispatch path (``_run_batch``) grouped by batch key — the slot
        loop's per-request decode state died with it, and the one-shot
        program recomputes from scratch, so retry/bisect/quarantine and
        "every future resolves" are inherited rather than re-implemented.
        Unsupervised: the legacy contract — every stranded future fails
        with the raw error."""
        from .supervisor import FailureClass

        sup = self.supervisor
        if sup is not None:
            cls = sup.classify(e)
            self.metrics.observe_failure(cls.value)
            sup.note_failure(cls)
            self._apply_rung()
            if not stranded:
                return
            if cls is FailureClass.FATAL:
                self._attempt_ctx = (time.monotonic(), 0.0, None)
                self._resolve_failed(stranded, e, cls)
                return
            delay = sup.backoff_s(1)
            self.metrics.observe_retry(len(stranded))
            self.metrics.observe_backoff(delay)
            for r in stranded:
                self._trace_fault(r, "retry", cls.value, delay)
            logger.warning(
                "retrying %d stranded request(s) via the one-shot path "
                "after %s loop failure (backoff %.3fs)",
                len(stranded), cls.value, delay,
            )
            time.sleep(delay)
            # group by batch key: residents share the dead loop's key, but
            # pending may already carry the NEXT key awaiting a loop switch
            # — mixing them in one generate would apply the head's params
            # to everyone
            groups: dict[tuple, list[ServeRequest]] = {}
            for r in stranded:
                groups.setdefault(r.batch_key(), []).append(r)
            for group in groups.values():
                self._run_batch(group)
            return
        now = time.monotonic()
        for r in stranded:
            adm = getattr(r, "inflight_admission", None)
            t0 = adm.admitted_at if adm is not None else now
            rec = ServeRequestRecord(
                request_id=r.request_id, status="error",
                trace_id=r.trace_id,
                queue_wait_s=max(t0 - r.enqueued_at, 0.0),
                engine_s=max(now - t0, 0.0),
                total_s=max(now - r.enqueued_at, 0.0),
                prompt_tokens=r.est_tokens,
            )
            self.metrics.observe_request(rec, tenant=r.tenant)
            self._fr("failed", rid=r.trace_id, reason="error")
            self._trace_request(r, t0, max(now - t0, 0.0), None, "error")
            self._release_preempt_pins(r)
            self._journal_fail(r, "error", str(e))
            if not r.future.done():
                r.future.set_exception(e)

    def recover_hung_dispatch(self, ticket) -> None:
        """Wedged slot-loop recovery — runs ON THE WATCHDOG THREAD while
        the scheduler thread is parked inside the hung ``admit``/``step``.

        One-shot tickets (the oversized-prompt fallback) take the base
        policy: riders fail typed HUNG. Slot kinds take the preemption
        machinery instead (PR 12): the hang is the LOOP's fault, not the
        riders', and their journaled ACCEPT payload is replayable — so the
        loop is torn down (evict all residents, prefix blocks PINNED so the
        restart prefill resumes warm, pins released at terminal resolution
        like any preemption), every resident and taken-but-unadmitted
        request is requeued, typed PREEMPTED/REQUEUED rides the journal,
        and the replacement thread rebuilds a fresh loop and completes them
        byte-identically (greedy; a sampled resident redraws its slot uid —
        the same caveat class as crash recovery). The parked thread is
        fenced by ``_stale_thread()``: its late return out of the closed
        loop touches nothing."""
        if ticket.kind == "one_shot":
            super().recover_hung_dispatch(ticket)
            return
        # FENCE FIRST (see the base override): the wedged thread reads
        # _stale_thread() == True from here on, so a hung admit/step that
        # limps back mid-recovery cannot race _pending or the dying loop
        successor = self._fence_replacement()
        stranded = list(self._pending)
        self._pending = []
        loop = self._live_loop
        evictions = []
        if loop is not None:
            residents = loop.outstanding()
            if residents:
                evictions = loop.evict(residents)
            self._close_loop(loop)
        logger.critical(
            "watchdog recovery: hung %s — tearing down the slot loop, "
            "requeueing %d resident(s) + %d pending",
            ticket.kind, len(evictions), len(stranded),
        )
        for ev in evictions:
            self._requeue_eviction(ev)
        for r in stranded:
            # taken off the queue but never slot-admitted: back it goes,
            # verbatim (no engine state to unwind, no preempt event owed)
            self.queue.requeue(r)
        self._note_hang_strike()
        self._start_replacement(successor)

    def _stranded_snapshot(self) -> list[ServeRequest]:
        stranded = list(self._pending)
        loop = self._live_loop
        if loop is not None:
            stranded.extend(loop.outstanding())
        # an oversized-prompt fallback batch mid-_run_batch is in-flight
        # work too (both for drain-overrun sheds and the cancel surface)
        stranded.extend(self._dispatching or [])
        return stranded

    def _take(self, loop, loop_key, active: int):
        """One queue interaction: blocking for the head when idle,
        non-blocking slot-feeding when decoding."""
        if not active:
            return self.queue.take_upto(
                self._take_limit(), wait_s=max(self.max_wait_s, 0.05)
            )
        if loop is None or not loop.free:
            return []
        head = self.queue.head_snapshot()
        if (
            head is not None
            and head[0] != loop_key
            and time.monotonic() - head[1] > self.switch_grace_s
        ):
            # an incompatible head has waited long enough: stop refilling
            # so the resident batch drains and the loop is rebuilt for it
            return []
        return self.queue.take_upto(loop.free, key=loop_key)

    def _cancel_sweep_inflight(self, loop) -> None:
        """Cancellation at the segment boundary — the in-flight half of the
        cancel contract: queued matches leave through the base sweep,
        taken-but-unadmitted ones resolve here (their DRR charge is
        credited back), and cancelled RESIDENTS are evicted through the
        same slot machinery preemption uses — but WITHOUT requeue and
        WITHOUT pinning their prefix (``evict(pin=False)``): a cancelled
        request is terminal, so warming its restart would pin blocks
        nobody will ever resume. Freed slots refill from the queue at this
        very boundary, which is what makes cancelling a saturating tenant
        hand the engine back within one segment."""
        if not self.cancellation_enabled:
            return
        if not self._cancelled_ids and self.stream_idle_timeout_s is None:
            return  # unlocked fast path, same contract as the base sweep
        self._cancel_sweep()
        live: list[ServeRequest] = []
        for r in self._pending:
            reason = self._cancel_reason_for(r)
            if reason is not None:
                self._resolve_cancelled(r, "queued", reason, taken=True)
            else:
                live.append(r)
        self._pending = live
        if loop is None or not loop.active:
            return
        victims = [
            (r, reason) for r in loop.outstanding()
            if (reason := self._cancel_reason_for(r)) is not None
        ]
        if not victims:
            return
        evictions = loop.evict([r for r, _ in victims], pin=False)
        reasons = {id(r): why for r, why in victims}
        for ev in evictions:
            r: ServeRequest = ev.key
            self._resolve_cancelled(
                r, "resident", reasons.get(id(r), "api")
            )
        if evictions:
            logger.info(
                "cancelled %d resident slot(s) at the segment boundary",
                len(evictions),
            )

    def _maybe_preempt(self, loop, loop_key) -> None:
        """Priority-tier preemption (serve/qos.py): when interactive work
        waits and the loop is saturated, evict batch-tier residents —
        release their slots, pin their prefix-cache blocks so the restart
        prefill resumes warm, journal a typed PREEMPTED, and requeue them
        through the journal's still-replayable ACCEPT state. The freed
        slots refill from the queue at this very segment boundary, and the
        WFQ pick hands them to the interactive tier first — an interactive
        burst reclaims the engine within one segment.

        Two demand signals: (a) queued interactive requests COMPATIBLE with
        the resident key — evict at least that many (bounded by the victims
        available); (b) an INCOMPATIBLE interactive head older than
        switch_grace_s — evict every batch resident so the loop drains and
        rebuilds for the new key instead of making the head wait out a
        long batch decode. Victims are chosen youngest-first (least decode
        work lost), each capped at ``preempt_budget`` lifetime evictions so
        sustained interactive pressure delays batch work but never starves
        it.

        Gang granularity (serve/gang.py): residents of one structured job
        are evicted WHOLE or not at all — a half-evicted fan-out strands
        the survivors' reduce behind a requeued sibling while the evictees
        hold prefix pins, the worst of both. Whole-gang eviction also bills
        the preempt budget per GANG: every member's counter moves in
        lockstep, and a gang with ANY member at budget is wholly
        non-evictable (the budget's starvation bound holds for the group
        exactly as it does for a lone request). Demand may be exceeded by
        gang granularity — deliberately. Ungrouped residents behave exactly
        as before."""
        if loop is None or not loop.active or self.queue.tenants is None:
            return

        def evictable(r: ServeRequest) -> bool:
            # greedy only: a restart recomputes byte-identically, which is
            # the losslessness contract. A SAMPLED row's stream keys on its
            # slot-admission uid — re-admission would draw a different
            # stream, so sampled batch requests keep their slots
            return r.preemptions < self.preempt_budget and (
                r.config is None
                or getattr(r.config, "temperature", 0.0) == 0.0
            )

        # group batch-tier residents by gang (ungrouped rows are their own
        # singleton group); a group is evictable only when EVERY member is
        groups: dict[str, list[ServeRequest]] = {}
        for i, r in enumerate(loop.outstanding()):
            if getattr(r, "tier", "") != "batch":
                continue
            gid = getattr(r, "gang_id", "") or f"solo#{i}"
            groups.setdefault(gid, []).append(r)
        evictable_groups = [
            (gid, members) for gid, members in groups.items()
            if all(evictable(r) for r in members)
        ]
        if not evictable_groups:
            return
        n_victims = sum(len(m) for _, m in evictable_groups)
        demand = 0
        if not loop.free:
            demand = self.queue.waiting_interactive(loop_key)
        head = self.queue.head_info()
        if (
            head is not None
            and head[0] != loop_key
            and head[2] != "batch"
            and time.monotonic() - head[1] > self.switch_grace_s
        ):
            # incompatible interactive head past grace: full drain — every
            # batch resident goes, the loop rebuilds for the new key
            demand = n_victims
        if demand <= 0:
            return

        # youngest-first: outstanding() is slot order; admission order is
        # tracked per-slot, so sort by admit time (newest residents lose
        # the least completed decode work). A GROUP's age is its youngest
        # member's — evicting the gang that joined last loses the least
        def admitted_at(r):
            adm = getattr(r, "inflight_admission", None)
            return adm.admitted_at if adm is not None else 0.0

        evictable_groups.sort(
            key=lambda g: max(admitted_at(r) for r in g[1]), reverse=True,
        )
        chosen: list[ServeRequest] = []
        gang_ids: list[str] = []
        for gid, members in evictable_groups:
            if len(chosen) >= demand:
                break
            chosen.extend(
                sorted(members, key=admitted_at, reverse=True)
            )
            if not gid.startswith("solo#"):
                gang_ids.append(gid)
        evictions = loop.evict(chosen)
        if not evictions:
            return
        if self._preempt_gap_s:
            # chaos kill window: eviction happened, PREEMPTED not yet
            # journaled — the crash point the soak's ledger audit covers
            time.sleep(self._preempt_gap_s)
        for ev in evictions:
            self._requeue_eviction(ev)
        for gid in gang_ids:
            self.gangs.note_preemption(gid)
        logger.info(
            "preempted %d batch-tier resident(s) for interactive demand"
            "%s",
            len(evictions),
            f" ({len(gang_ids)} whole gang(s))" if gang_ids else "",
        )

    def _requeue_eviction(self, ev) -> None:
        """THE eviction -> requeue bookkeeping, shared by tier preemption
        (_maybe_preempt) and watchdog hang recovery so the two can never
        drift: preemption count (it bills the preempt_budget starvation
        bound either way — a request repeatedly displaced by hang recovery
        is just as starved), pin carry, typed PREEMPTED/REQUEUED journal
        events, metrics, flight-recorder events, and the trace span."""
        r: ServeRequest = ev.key
        r.preemptions += 1
        if ev.pin is not None:
            r.preempt_pins.append(ev.pin)
        if self.journal is not None and r.journal_rid is not None:
            self.journal.preempt(r.journal_rid)
        self.metrics.observe_preemption(tenant=r.tenant)
        self._fr("preempt", rid=r.trace_id, tenant=r.tenant,
                 preemptions=r.preemptions)
        self._trace_fault(r, "preempt", None, 0.0)
        self.queue.requeue(r)
        if self.journal is not None and r.journal_rid is not None:
            self.journal.requeue(r.journal_rid)
        self.metrics.observe_requeue(tenant=r.tenant)
        self._fr("requeue", rid=r.trace_id, tenant=r.tenant)

    def _make_loop(self, head: ServeRequest):
        loop = self.backend.start_slot_loop(
            self._take_limit(),
            max_new_tokens=head.max_new_tokens,
            config=head.config,
            prompt_tokens=self.slot_prompt_tokens,
            fused_segments=self.fused_segments,
        )
        self._live_loop = loop
        return loop

    def _close_loop(self, loop) -> None:
        if loop is not None:
            self._live_loop = None
            loop.close()

    def _evict_all(self, loop, pending: list[ServeRequest]):
        """Collect every request still owed an answer after a loop failure."""
        stranded = list(pending)
        if loop is not None:
            stranded.extend(loop.outstanding())
            self._close_loop(loop)
        self._live_loop = None
        return stranded

    # -- admission ---------------------------------------------------------

    def _admit(self, loop, pending: list[ServeRequest]) -> list[ServeRequest]:
        now = time.monotonic()
        live: list[ServeRequest] = []
        for r in pending:
            reason = self._cancel_reason_for(r)
            if reason is not None:
                # cancelled between take and slot admission: resolve before
                # any prefill work, crediting the DRR charge the take made
                self._resolve_cancelled(r, "queued", reason, taken=True)
            elif r.expired(now):
                # the queue sheds expired requests it still holds; taken-but
                # -unadmitted ones are this scheduler's to shed — including
                # the owned-trace finalization the queue-side _on_shed hook
                # performs, so SLO-miss requests still reach /debug/trace
                self._shed_taken(r, ShedReason.DEADLINE)
            else:
                live.append(r)
        pending = live
        if not pending or not loop.free:
            return pending
        was_running = loop.active > 0
        items = [(r, r.prompt, r.cache_hint) for r in pending[: loop.free]]
        # bounded-dispatch contract: slot admission runs the joiners'
        # chunked prefill — token-scaled budget like a one-shot dispatch
        ticket = self._wd_begin("slot_admit", [r for r, _p, _h in items])
        try:
            admissions, rejected = loop.admit(items)
        finally:
            self._wd_end(ticket)
        if self._stale_thread():
            # the watchdog declared this admit hung, requeued every pending
            # request, and replaced this thread: the late admissions belong
            # to a torn-down loop
            return []
        admitted_ids = {id(a.key) for a in admissions}
        rejected_ids = {id(k) for k in rejected}
        for adm in admissions:
            r: ServeRequest = adm.key
            r.inflight_admission = adm  # read back at harvest
            if self.journal is not None and r.journal_rid is not None:
                # slot admission IS this request's engine start: its own
                # prefill ran (the one-shot path journals START per batch
                # dispatch in _dispatch instead)
                self.journal.start(r.journal_rid)
        if admissions:
            prefill_s = admissions[0].prefill_end - admissions[0].admitted_at
            self.metrics.observe_batch(len(admissions), prefill_s)
            if self.recorder is not None:
                # guarded, not _fr: the riders list must not be built on
                # the recorder-less hot path (the all-off arm's contract)
                self.recorder.record(
                    "dispatch", rid=admissions[0].key.trace_id,
                    occupancy=len(admissions), slot_admit=True,
                    rids=[a.key.trace_id for a in admissions[1:]])
            if was_running:
                self.metrics.observe_refill(len(admissions))
        if rejected:
            # prompts beyond the loop's S bucket: classic batch dispatch
            # between segments (residents wait one blocking generate —
            # bounded by the oversized request itself, and the one-shot
            # program stays the path of record for it)
            fallback = [r for r in pending if id(r) in rejected_ids]
            logger.info(
                "dispatching %d oversized request(s) via the one-shot path",
                len(fallback),
            )
            self._run_batch(fallback)
        return [
            r for r in pending
            if id(r) not in admitted_ids and id(r) not in rejected_ids
        ]

    # -- segment + harvest --------------------------------------------------

    def _run_segment(self, loop) -> None:
        # bounded-dispatch contract: one decode segment is bounded work
        # whatever the residents' prompts cost — flat segment budget.
        # Deliberately rider-free: segments are the per-token-scale hot
        # path, and recovery re-reads loop.outstanding() itself — a tuple
        # of trace ids per segment would be allocation for a report field
        ticket = None
        if self.watchdog is not None:
            # N-scaled: a fused dispatch holds the host for up to N
            # segments of legitimate work — budget accordingly, so fusing
            # never manufactures a false HUNG (and a real hang still trips
            # after N segment budgets)
            ticket = self.watchdog.begin_dispatch(
                "scheduler", "slot_segment",
                self.watchdog.segment_budget(self.fused_segments),
            )
        try:
            res = loop.step()
        finally:
            self._wd_end(ticket)
        if self._stale_thread():
            # hung segment: the watchdog already evicted + requeued every
            # RESIDENT and replaced this thread — but rows that finished in
            # this very segment left the slots before the eviction saw
            # them, so their futures are nobody else's to resolve: hand
            # them back (recompute is byte-identical; a rider recovery DID
            # resolve is a done-guarded no-op)
            self._requeue_stale([c.key for c in res.completions])
            return
        self.metrics.observe_segment(
            res.live, res.seconds, res.new_tokens,
            device_segments=getattr(res, "device_segments", 1),
        )
        now = time.monotonic()
        self._emit_stream_deltas(loop)
        for c in res.completions:
            r: ServeRequest = c.key
            adm = getattr(r, "inflight_admission", None)
            t_admit = adm.admitted_at if adm is not None else now
            engine_s = now - t_admit
            rec = ServeRequestRecord(
                request_id=r.request_id,
                status="ok",
                trace_id=r.trace_id,
                queue_wait_s=max(t_admit - r.enqueued_at, 0.0),
                engine_s=engine_s,
                total_s=max(now - r.enqueued_at, 0.0),
                # TTFT anchored at the JOINER's own prefill end — the whole
                # point of refill: first-token time no longer includes
                # strangers' decode
                ttft_s=max(
                    (adm.prefill_end if adm is not None else now)
                    - r.enqueued_at, 0.0,
                ),
                ttft_anchored=adm is not None,
                batch_size=adm.occupancy if adm is not None else res.live,
                prompt_tokens=r.est_tokens,
                generated_tokens=c.gen_tokens,
            )
            rec.cached_prompt_tokens = (
                adm.cached_tokens if adm is not None else 0
            )
            self.metrics.observe_request(rec, tenant=r.tenant)
            self._fr("complete", rid=r.trace_id, gen_tokens=c.gen_tokens)
            self._trace_request(r, t_admit, engine_s, None, "ok")
            self._release_preempt_pins(r)
            if r.stream is not None:
                # final harvest text through the same delta path: whatever
                # the per-segment snapshots didn't emit leaves here, so
                # concatenated deltas == the completion text, BEFORE the
                # future resolves (the handler drains after done)
                r.stream.push_text(c.text)
            if self.journal is not None and r.journal_rid is not None:
                # ledger before future, same ordering rationale as the
                # one-shot path in scheduler._dispatch
                self.journal.complete(r.journal_rid, c.text, c.gen_tokens)
            if not r.future.done():
                r.future.set_result(_Completion(c.text, rec))

    def _emit_stream_deltas(self, loop) -> None:
        """Per-segment streaming harvest: fetch the decoded-so-far text of
        every STREAMING resident (one host fetch per segment, only when
        streaming requests are actually resident) and push the suffix
        deltas into their channels. The first delta journals the STREAMING
        lifecycle event."""
        streams = [
            r for r in loop.outstanding()
            if getattr(r, "stream", None) is not None
        ]
        if not streams:
            return
        partials = loop.partial_outputs(streams)  # keyed by id(request)
        for r in streams:
            text = partials.get(id(r))
            if text and r.stream.push_text(text) and not r.stream_journaled:
                r.stream_journaled = True
                if self.journal is not None and r.journal_rid is not None:
                    self.journal.streaming(r.journal_rid)
