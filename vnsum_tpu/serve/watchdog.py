"""Watchdog: hang/stall detection and wedged-dispatch recovery.

The supervisor (serve/supervisor.py) recovers from engine *exceptions* and
the journal (serve/journal.py) from *crashes* — but a dispatch that simply
never RETURNS (a stuck device op, a pathological compile, a lock wait, a
wedged helper thread) freezes the scheduler silently: no exception fires,
``/healthz`` keeps reporting ok, and every client rides out its own
deadline. This module is the liveness layer closing that gap, in two parts:

**Heartbeat registry.** Every long-lived serving thread registers a named
:class:`Heartbeat` with a per-thread deadline and beats it once per loop
iteration (the scheduler loop beats from inside the queue's wait loops, so
an idle server still ticks; the SLO monitor beats per evaluation). A
heartbeat older than its deadline is a STALL.

**Bounded-dispatch contract.** Each engine dispatch is stamped with a
:class:`DispatchTicket` carrying a wall-clock budget derived from its token
work (``dispatch_budget()``: base + per-token seconds — a 64-row prefill
legitimately takes longer than a one-row decode segment, so budgets scale
with the work instead of a one-size timeout). While a ticket is armed the
owner's heartbeat check is SUSPENDED — the loop can't beat mid-dispatch,
and a slow-but-progressing dispatch inside its budget must never be
flagged (the false-positive-immunity contract) — and a ticket past its
budget is declared HUNG.

On a stall the monitor thread:

(a) **snapshots every thread's stack** (``sys._current_frames``) into a
    typed ``stall`` flight-recorder event and an on-disk
    ``watchdog_<kind>_<utc-ms>_<n>.json`` dump (atomic write, same crash
    discipline as the flight recorder's);
(b) **classifies** it: ``dispatch`` (a ticket over budget), ``helper`` (a
    helper-kind heartbeat went quiet), or ``lock`` (a loop-kind heartbeat
    went quiet with NO dispatch armed — the thread is wedged in a lock /
    condition / fsync wait somewhere outside the engine);
(c) **recovers**: dispatch stalls invoke ``on_hung_dispatch`` — the
    scheduler's recovery hook (riders of a hung one-shot dispatch resolve
    typed ``RequestFailed(HUNG)``; a hung slot loop is torn down and its
    residents requeued through the journal's replayable ACCEPT, the
    preemption machinery — and the scheduler thread is REPLACED, the
    abandoned one fenced off by a stale-thread check at every boundary);
    lock and helper stalls invoke ``on_escalate`` — the HTTP server wires
    a supervised journal-seal-and-exit (``WATCHDOG_EXIT_CODE``) so an
    outer process manager restarts and journal replay restores state. A
    recovery also charges the degradation ladder a resource strike via the
    scheduler hook: a host that hangs dispatches is a host running too hot.

Threading: ``beat()`` and ticket begin/end are the hot-path writes — beat
is ONE attribute store (no lock; the monitor's racy read is a float, and a
stale read delays detection by one interval, never corrupts), tickets take
the ``serve.watchdog`` lock briefly. The monitor holds the lock only to
COLLECT stalls; dumps, recorder appends, and recovery callbacks all run
outside it (recovery acquires queue/journal/radix locks, so the watchdog
lock must stay leaf-like for the lock-order sanitizer). Detection math is
clock-injectable (``clock=``) so tests drive it synthetically without
sleeping.
"""
from __future__ import annotations

import itertools
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field
from pathlib import Path

from ..analysis.sanitizers import make_lock
from ..core.artifacts import atomic_write_json
from ..core.logging import get_logger

logger = get_logger("vnsum.serve.watchdog")

# the supervised-escalation exit status (journal sealed best-effort, state
# restorable by replay): distinct from crash (-9) and clean drain (0) so a
# process manager / the chaos harness can tell "the watchdog gave up on
# this process" from everything else
WATCHDOG_EXIT_CODE = 86

# classification vocabulary — the stable label set of
# vnsum_serve_watchdog_stalls_total{kind}
STALL_KINDS = ("dispatch", "lock", "helper")

_dump_ids = itertools.count(1)


def snapshot_stacks() -> list[dict]:
    """Every live thread's Python stack, JSON-shaped — the one snapshot
    serving ``GET /debug/stacks``, the SIGUSR1 handler, and the watchdog's
    automatic stall dumps. ``sys._current_frames`` is a point-in-time copy;
    frames may advance while formatting, which is fine for a post-mortem."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        out.append({
            "ident": ident,
            "name": t.name if t is not None else f"thread-{ident}",
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": [ln.rstrip("\n") for ln in traceback.format_stack(frame)],
        })
    out.sort(key=lambda d: d["name"])
    return out


class Heartbeat:
    """One registered thread's liveness stamp. ``beat()`` is the hot-path
    write: a single attribute store, no lock — the monitor's read races it
    harmlessly (floats are atomic; staleness delays detection by at most
    one interval)."""

    __slots__ = ("name", "kind", "deadline_s", "last_beat", "_clock")

    def __init__(self, name: str, kind: str, deadline_s: float,
                 clock) -> None:
        self.name = name
        self.kind = kind  # "loop" | "helper"
        self.deadline_s = float(deadline_s)
        self._clock = clock
        self.last_beat = clock()

    def beat(self) -> None:
        self.last_beat = self._clock()

    def age(self, now: float | None = None) -> float:
        return (self._clock() if now is None else now) - self.last_beat


@dataclass
class DispatchTicket:
    """One in-flight engine dispatch under the bounded-dispatch contract."""

    owner: str            # heartbeat name of the dispatching thread
    kind: str             # "one_shot" | "slot_admit" | "slot_segment"
    budget_s: float
    started_at: float
    riders: tuple = ()    # trace ids, for the stall report
    tokens: int = 0

    def age(self, now: float) -> float:
        return now - self.started_at


@dataclass
class Stall:
    """One classified liveness verdict, handed to dumps and recovery."""

    kind: str             # "dispatch" | "lock" | "helper"
    name: str             # heartbeat / owner name
    stalled_for_s: float
    limit_s: float        # the budget or deadline that was exceeded
    ticket: DispatchTicket | None = None
    detail: dict = field(default_factory=dict)


class Watchdog:
    """Heartbeat registry + bounded-dispatch monitor + stall recovery."""

    def __init__(
        self,
        *,
        interval_s: float = 0.5,
        loop_deadline_s: float = 10.0,
        helper_deadline_s: float = 60.0,
        dispatch_base_s: float = 30.0,
        dispatch_per_token_s: float = 0.01,
        segment_budget_s: float | None = None,
        clock=time.monotonic,
        recorder=None,
        dump_dir: str | Path | None = None,
        on_escalate=None,
    ) -> None:
        self.interval_s = float(interval_s)
        self.loop_deadline_s = float(loop_deadline_s)
        self.helper_deadline_s = float(helper_deadline_s)
        self.dispatch_base_s = float(dispatch_base_s)
        self.dispatch_per_token_s = float(dispatch_per_token_s)
        # a decode segment is bounded work whatever the resident prompts
        # cost to prefill — its budget is the base, not token-scaled
        self.segment_budget_s = (
            float(segment_budget_s) if segment_budget_s is not None
            else self.dispatch_base_s
        )
        self._clock = clock
        self.recorder = recorder
        self.dump_dir = Path(dump_dir) if dump_dir else None
        # dispatch stalls: the scheduler registers its recovery here
        # (riders typed HUNG / slot-loop teardown + requeue + respawn).
        # lock/helper stalls: on_escalate — the server wires a supervised
        # journal-seal-and-exit; None (library/test default) just dumps
        self.on_hung_dispatch = None
        self.on_escalate = on_escalate
        # leaf-like by contract: held only for registry/ticket bookkeeping
        # and stall COLLECTION — never while dumping, recording, or
        # recovering (those take queue/journal/radix locks)
        self._lock = make_lock("serve.watchdog")
        self._beats: dict[str, Heartbeat] = {}        # guarded by: _lock
        self._tickets: dict[str, DispatchTicket] = {}  # guarded by: _lock
        self._flagged: set[str] = set()               # guarded by: _lock
        # monotone counters; racy scrape reads are fine
        self.stalls_total: dict[str, int] = {k: 0 for k in STALL_KINDS}
        self.recoveries_total = 0
        self.hung_dispatches_total = 0
        self.dumps_written = 0
        self.last_stall: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def now(self) -> float:
        """The watchdog's own clock — callers doing arithmetic against
        ticket/heartbeat timestamps (which live in THIS clock's space,
        possibly synthetic under test) must use it, never a bare
        ``time.monotonic()``."""
        return self._clock()

    # -- registry ---------------------------------------------------------

    def register(self, name: str, *, kind: str = "loop",
                 deadline_s: float | None = None) -> Heartbeat:
        """Register (or re-register: a respawned thread keeps its name)
        one long-lived thread; returns the handle it must beat()."""
        if deadline_s is None:
            deadline_s = (self.helper_deadline_s if kind == "helper"
                          else self.loop_deadline_s)
        hb = Heartbeat(name, kind, deadline_s, self._clock)
        with self._lock:
            self._beats[name] = hb
            self._flagged.discard(name)
        return hb

    def unregister(self, name: str) -> None:
        """A clean thread exit (drain) stops being monitored — a drained
        scheduler must not read as a stall."""
        with self._lock:
            self._beats.pop(name, None)
            self._tickets.pop(name, None)
            self._flagged.discard(name)

    # -- bounded-dispatch contract ----------------------------------------

    def dispatch_budget(self, tokens: int) -> float:
        """Wall-clock budget for a dispatch over ``tokens`` of work
        (prompt + expected decode): base + per-token seconds."""
        return self.dispatch_base_s + self.dispatch_per_token_s * max(
            int(tokens), 0
        )

    def segment_budget(self, fused_segments: int = 1) -> float:
        """Wall-clock budget for one slot-loop decode dispatch covering
        ``fused_segments`` on-device segments: the flat per-segment budget
        scaled by N. A fused dispatch legitimately holds the host N times
        longer than a single segment — without the scaling every fused
        dispatch slower than one segment's budget would be a false HUNG,
        and with it a genuinely wedged dispatch still trips after N
        budgets."""
        return self.segment_budget_s * max(int(fused_segments), 1)

    def begin_dispatch(self, owner: str, kind: str, budget_s: float,
                       riders: tuple = (), tokens: int = 0) -> DispatchTicket:
        t = DispatchTicket(owner=owner, kind=kind, budget_s=float(budget_s),
                           started_at=self._clock(), riders=tuple(riders),
                           tokens=int(tokens))
        with self._lock:
            self._tickets[owner] = t
        return t

    def end_dispatch(self, ticket: DispatchTicket | None) -> None:
        """Clear the ticket — a no-op when the watchdog already declared it
        hung and removed it (the abandoned thread's late return)."""
        if ticket is None:
            return
        with self._lock:
            if self._tickets.get(ticket.owner) is ticket:
                del self._tickets[ticket.owner]

    # -- detection --------------------------------------------------------

    def check(self, now: float | None = None) -> list[Stall]:
        """Pure-ish detection pass: classify every over-limit thread and
        return the stalls (each flagged once — a wedged thread re-fires
        only after it beats again or its hung ticket is replaced). Called
        by the monitor thread; tests call it with a synthetic clock."""
        if now is None:
            now = self._clock()
        out: list[Stall] = []
        with self._lock:
            hung_owners: set[str] = set()
            for owner, t in list(self._tickets.items()):
                age = t.age(now)
                if age <= t.budget_s:
                    continue
                # declared hung: remove it so end_dispatch from the
                # abandoned thread no-ops and the next interval doesn't
                # re-declare the same dispatch
                del self._tickets[owner]
                hung_owners.add(owner)
                # one stall, one verdict: the owner's heartbeat is stale
                # BECAUSE it was dispatching — restamp it so neither this
                # pass nor the next misreads the same wedge as a second,
                # lock-classified stall while recovery (which replaces the
                # thread and re-beats) is still running
                hb = self._beats.get(owner)
                if hb is not None:
                    hb.beat()
                out.append(Stall(
                    kind="dispatch", name=owner, stalled_for_s=age,
                    limit_s=t.budget_s, ticket=t,
                    detail={"dispatch_kind": t.kind, "tokens": t.tokens,
                            "riders": list(t.riders)[:32]},
                ))
            for name, hb in self._beats.items():
                if name in self._tickets or name in hung_owners:
                    # mid-dispatch: the loop cannot beat; the ticket's
                    # budget governs (false-positive immunity)
                    continue
                age = hb.age(now)
                if age <= hb.deadline_s:
                    # healthy (it beat since): clear any standing flag so a
                    # FUTURE stall of the same thread is a new verdict
                    self._flagged.discard(name)
                    continue
                if name in self._flagged:
                    continue  # already declared; re-fire only after a beat
                self._flagged.add(name)
                out.append(Stall(
                    kind="helper" if hb.kind == "helper" else "lock",
                    name=name, stalled_for_s=age, limit_s=hb.deadline_s,
                ))
        return out

    # -- stall handling ---------------------------------------------------

    def handle(self, stall: Stall) -> None:
        """One stall end to end: count, snapshot stacks (in-memory —
        cheap), RECOVER (dispatch) or escalate (lock/helper), then write
        the dumps. Recovery runs BEFORE disk I/O on purpose: the scheduler
        hook's first act is to fence off the wedged thread, and a dispatch
        that limps back at budget+epsilon must meet that fence within the
        microseconds of the snapshot, not after tens of milliseconds of
        atomic-write fsync. Runs OUTSIDE the watchdog lock."""
        self.stalls_total[stall.kind] = (
            self.stalls_total.get(stall.kind, 0) + 1
        )
        self.last_stall = {
            "kind": stall.kind, "name": stall.name,
            "stalled_for_s": round(stall.stalled_for_s, 3),
            "limit_s": round(stall.limit_s, 3),
            "t_wall": time.time(),
        }
        logger.critical(
            "watchdog: %s stall on %r — %.2fs past a %.2fs %s",
            stall.kind, stall.name, stall.stalled_for_s, stall.limit_s,
            "budget" if stall.kind == "dispatch" else "heartbeat deadline",
        )
        stacks = snapshot_stacks()
        if self.recorder is not None:
            self.recorder.record(
                "stall", rid=(stall.ticket.riders[0] if stall.ticket is not None
                              and stall.ticket.riders else ""),
                stall_kind=stall.kind, thread=stall.name,
                stalled_for_s=round(stall.stalled_for_s, 3),
                limit_s=round(stall.limit_s, 3),
            )
        recovered = False
        if stall.kind == "dispatch":
            self.hung_dispatches_total += 1
            hook = self.on_hung_dispatch
            if hook is not None:
                try:
                    hook(stall.ticket)
                    self.recoveries_total += 1
                    recovered = True
                    if self.recorder is not None:
                        self.recorder.record(
                            "watchdog_recover", stall_kind=stall.kind,
                            thread=stall.name,
                        )
                # lint-allow[swallowed-exception]: a failed recovery falls through to escalation below — the stall is still answered, just with the bigger hammer
                except Exception:
                    logger.exception("watchdog: dispatch recovery failed; "
                                     "escalating")
        self.dump_stall(stall, stacks)
        if self.recorder is not None:
            # the ring now holds the stall (and any recover) event plus the
            # lead-up — snapshot it like every other anomaly (throttled)
            self.recorder.dump("stall")
        if not recovered:
            self._escalate(stall)

    def _escalate(self, stall: Stall) -> None:
        hook = self.on_escalate
        if hook is None:
            # library/test default: the dump IS the response; embedding
            # callers that want seal-and-exit wire on_escalate (the HTTP
            # server does)
            logger.critical("watchdog: no escalation handler configured "
                            "for %s stall on %r", stall.kind, stall.name)
            return
        hook(stall)

    def dump_stall(self, stall: Stall, stacks: list[dict]) -> Path | None:
        """``watchdog_<kind>_<utc-ms>_<n>.json``: the stall verdict plus
        every thread's stack — the automatic twin of ``GET /debug/stacks``.
        None when no dump_dir is configured; a full disk must not turn a
        stall report into a second failure."""
        if self.dump_dir is None:
            return None
        payload = {
            "reason": f"watchdog_{stall.kind}",
            "stall": {
                "kind": stall.kind,
                "thread": stall.name,
                "stalled_for_s": round(stall.stalled_for_s, 3),
                "limit_s": round(stall.limit_s, 3),
                **stall.detail,
            },
            "dumped_wall": time.time(),
            "heartbeats": self.heartbeat_ages(),
            "stacks": stacks,
        }
        path = self.dump_dir / (
            f"watchdog_{stall.kind}_{int(time.time() * 1000)}"
            f"_{next(_dump_ids):03d}.json"
        )
        try:
            atomic_write_json(path, payload)
        except OSError:
            logger.exception("watchdog stack dump to %s failed", path)
            return None
        self.dumps_written += 1
        logger.warning("watchdog: wrote stack dump %s", path)
        return path

    # -- surfaces ---------------------------------------------------------

    def heartbeat_ages(self, now: float | None = None) -> dict[str, float]:
        """Last-beat age per registered thread — the /healthz watchdog line
        and the heartbeat_age_seconds gauges."""
        if now is None:
            now = self._clock()
        with self._lock:
            return {
                name: round(max(hb.age(now), 0.0), 3)
                for name, hb in sorted(self._beats.items())
            }

    def health_dict(self) -> dict:
        out: dict = {
            "threads": self.heartbeat_ages(),
            "stalls_total": sum(self.stalls_total.values()),
            "recoveries_total": self.recoveries_total,
        }
        if self.last_stall is not None:
            out["last_stall"] = self.last_stall
        return out

    def stats_dict(self) -> dict:
        """Scrape-time counters for /metrics (vnsum_serve_watchdog_*)."""
        return {
            "stalls": dict(self.stalls_total),
            "recoveries": self.recoveries_total,
            "hung_dispatches": self.hung_dispatches_total,
            "heartbeat_ages": self.heartbeat_ages(),
        }

    # -- monitor thread ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._monitor, name="vnsum-serve-watchdog", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def tick(self, now: float | None = None) -> list[Stall]:
        """One detection + handling pass (what the monitor runs per
        interval; tests call it directly under a synthetic clock)."""
        stalls = self.check(now)
        for s in stalls:
            self.handle(s)
        return stalls

    def _monitor(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            # lint-allow[swallowed-exception]: the monitor is the last line of liveness defense — a detection bug must not kill it (the next tick retries) and there is no request to resolve
            except Exception:
                logger.exception("watchdog tick failed; continuing")
