"""Per-request streaming emit channel: scheduler harvest -> HTTP response.

The in-flight slot loop already surfaces every segment boundary to the host
(serve/inflight.py::_run_segment) — streaming is "only" the plumbing from
that boundary to the client socket. A :class:`StreamChannel` is that pipe:
the SCHEDULER thread pushes text snapshots as a request's decode advances
(and the harvest's final text at completion), the HTTP handler thread pops
delta events and writes them as SSE frames. The channel never blocks the
scheduler: pushes are queue puts, and a slow/disconnected client only grows
its own channel, never a decode segment.

Delta discipline — what makes ``"".join(deltas) == final_text`` a hard
invariant rather than a hope:

- ``push_text`` takes the FULL decoded text so far and emits only the
  suffix beyond what was already emitted;
- a snapshot that does not extend the emitted prefix (preemption restarted
  the request from scratch; a tokenizer boundary re-rendered a partial
  piece) emits NOTHING — emission resumes once decode re-passes the
  high-water mark, and the completion push flushes whatever remains;
- the completion's text goes through the same path, so the concatenation
  identity holds for every request, including preempted-and-requeued ones.

The channel carries no terminal sentinel: the HTTP layer already holds the
request future (or the summarize worker thread) and drains the channel
after it resolves — resolution ordering in the scheduler (deltas pushed
BEFORE the future) makes that race-free.
"""
from __future__ import annotations

import queue


class StreamChannel:
    """One request's emit channel. Producer: the scheduler thread (pushes
    are in dispatch/harvest order). Consumer: the HTTP handler thread."""

    def __init__(self, request_id: str = "") -> None:
        self.request_id = request_id
        self._q: queue.Queue = queue.Queue()
        # producer-side high-water mark of emitted text; scheduler-thread
        # only, like the rest of the engine-side request state
        self._sent = ""
        self.events_pushed = 0

    # -- producer side (scheduler thread) ---------------------------------

    def push_text(self, text_so_far: str) -> bool:
        """Emit the suffix of ``text_so_far`` beyond what was already
        emitted; returns True when a delta actually left. Non-extending
        snapshots (preemption restart, re-rendered partial detok) emit
        nothing — see the module docstring's delta discipline."""
        if (
            not text_so_far
            or not text_so_far.startswith(self._sent)
            or len(text_so_far) <= len(self._sent)
        ):
            return False
        delta = text_so_far[len(self._sent):]
        self._sent = text_so_far
        self.events_pushed += 1
        self._q.put(("delta", {"text": delta}))
        return True

    def push_event(self, kind: str, payload: dict) -> None:
        """Out-of-band event (summarize round progress etc.)."""
        self.events_pushed += 1
        self._q.put((kind, dict(payload)))

    # -- consumer side (HTTP handler thread) ------------------------------

    def pop(self, timeout_s: float) -> tuple[str, dict] | None:
        try:
            return self._q.get(timeout=timeout_s)
        # lint-allow[swallowed-exception]: an empty poll IS the answer — the caller re-checks the request future and keeps draining
        except queue.Empty:
            return None

    def empty(self) -> bool:
        return self._q.empty()
