"""Per-request streaming emit channel: scheduler harvest -> HTTP response.

The in-flight slot loop already surfaces every segment boundary to the host
(serve/inflight.py::_run_segment) — streaming is "only" the plumbing from
that boundary to the client socket. A :class:`StreamChannel` is that pipe:
the SCHEDULER thread pushes text snapshots as a request's decode advances
(and the harvest's final text at completion), the HTTP handler thread pops
delta events and writes them as SSE frames. The channel never blocks the
scheduler: pushes never wait, and a slow/disconnected client only grows its
own channel up to ``maxsize`` — past that, pending same-kind events are
COALESCED (deltas concatenate, progress keeps the latest), so a wedged
consumer costs one bounded buffer, never unbounded memory.

Delta discipline — what makes ``"".join(deltas) == final_text`` a hard
invariant rather than a hope:

- ``push_text`` takes the FULL decoded text so far and emits only the
  suffix beyond what was already emitted;
- a snapshot that does not extend the emitted prefix (preemption restarted
  the request from scratch; a tokenizer boundary re-rendered a partial
  piece) emits NOTHING — emission resumes once decode re-passes the
  high-water mark, and the completion push flushes whatever remains;
- the completion's text goes through the same path, so the concatenation
  identity holds for every request, including preempted-and-requeued ones;
- coalescing concatenates ADJACENT pending deltas in order, which is the
  identity's own operation — a coalesced stream reassembles byte-identically.

Resume (serve/server.py ``Last-Event-ID``): every event carries a monotone
``seq``; ``emitted_text`` snapshots the producer high-water mark, so a
reconnecting client gets one full-text ``snapshot`` event and then live
deltas. ``attach()`` hands the channel to the NEW consumer — a previous
handler still blocked on ``pop`` gets :class:`StreamDetached` and exits
without writing a terminal frame. ``last_consumed`` (refreshed by every pop
and attach) is the idle-consumer clock the scheduler's disconnect sweep
cancels on.

The channel carries no terminal sentinel: the HTTP layer already holds the
request future (or the summarize worker thread) and drains the channel
after it resolves — resolution ordering in the scheduler (deltas pushed
BEFORE the future) makes that race-free.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ..analysis.sanitizers import make_lock


class StreamDetached(RuntimeError):
    """Raised out of ``pop`` to a consumer whose attachment was superseded
    (a reconnecting client called ``attach``) — the stale handler must stop
    draining and exit WITHOUT writing a terminal event."""


class StreamChannel:
    """One request's emit channel. Producer: the scheduler thread (pushes
    are in dispatch/harvest order). Consumer: the HTTP handler thread — at
    most ONE live consumer at a time (``attach`` supersedes)."""

    def __init__(self, request_id: str = "", maxsize: int = 256,
                 metrics=None) -> None:
        self.request_id = request_id
        self.maxsize = max(int(maxsize), 2)
        # backpressure-coalesce observer (ServeMetrics) — the channel calls
        # observe_stream_coalesced under its own lock; the metrics lock is
        # a leaf in the lock-order graph, so stream -> metrics is safe
        self.metrics = metrics
        # lock-order-sanitizer hook: plain threading.Lock in production
        # _cond wraps _lock (one underlying mutex, two names); the
        # guarded-by annotations list both so either entry form satisfies
        # the lint — same convention as serve/queue.py
        self._lock = make_lock("serve.stream")
        self._cond = threading.Condition(self._lock)
        self._q: deque = deque()      # guarded by: _cond, _lock
        self._seq = 0                 # guarded by: _cond, _lock
        self._sent = ""               # guarded by: _cond, _lock
        self._closed = False          # guarded by: _cond, _lock
        self._gen = 0                 # guarded by: _cond, _lock
        self.events_pushed = 0
        self.coalesced = 0
        # idle-consumer clock: refreshed by every pop/attach; read lock-free
        # by the scheduler's disconnect sweep (a stale float read only
        # delays one sweep iteration, never corrupts)
        self.last_consumed = time.monotonic()

    # -- producer side (scheduler thread) ---------------------------------

    def _append_locked(self, kind: str, payload: dict) -> None:
        if self._closed:
            return  # dead stream: the consumer is gone for good, drop
        self._seq += 1
        self._q.append((kind, payload, self._seq))
        self.events_pushed += 1
        if len(self._q) >= self.maxsize:
            self._coalesce_locked()
        self._cond.notify_all()

    def _coalesce_locked(self) -> None:
        """Collapse pending same-kind runs: adjacent deltas concatenate into
        one (the concatenation identity's own operation, so reassembly is
        unaffected); for other kinds (progress) only the LATEST of a run
        survives — their payloads are monotone snapshots. Each merged event
        keeps the run's newest seq, so resume ids stay monotone.

        If adjacent merging alone cannot get back under the bound (a
        pathological alternation like delta/progress/delta/...), collapse
        GLOBALLY: one delta event carrying every pending delta in order
        (identity still intact) plus the latest event of each other kind —
        the queue then holds at most one event per kind, a hard bound, so
        a wedged consumer can never make this pass quadratic either."""
        merged: deque = deque()
        dropped = 0
        for kind, payload, seq in self._q:
            if merged and merged[-1][0] == kind:
                last_kind, last_payload, _last_seq = merged[-1]
                if kind == "delta":
                    payload = {
                        **payload,
                        "text": last_payload["text"] + payload["text"],
                    }
                merged[-1] = (kind, payload, seq)
                dropped += 1
            else:
                merged.append((kind, payload, seq))
        if len(merged) >= self.maxsize:
            slots: dict[str, int] = {}  # kind -> index in the output
            flat: list = []
            for kind, payload, seq in merged:
                at = slots.get(kind)
                if at is None:
                    slots[kind] = len(flat)
                    flat.append((kind, dict(payload), seq))
                else:
                    prev = flat[at][1]
                    if kind == "delta":
                        payload = {**payload,
                                   "text": prev["text"] + payload["text"]}
                    flat[at] = (kind, dict(payload), seq)
                    dropped += 1
            merged = deque(flat)
        self._q = merged
        if dropped:
            self.coalesced += dropped
            if self.metrics is not None:
                self.metrics.observe_stream_coalesced(dropped)

    def push_text(self, text_so_far: str) -> bool:
        """Emit the suffix of ``text_so_far`` beyond what was already
        emitted; returns True when a delta actually left. Non-extending
        snapshots (preemption restart, re-rendered partial detok) emit
        nothing — see the module docstring's delta discipline."""
        with self._cond:
            if (
                not text_so_far
                or not text_so_far.startswith(self._sent)
                or len(text_so_far) <= len(self._sent)
            ):
                return False
            delta = text_so_far[len(self._sent):]
            self._sent = text_so_far
            self._append_locked("delta", {"text": delta})
            return True

    def push_event(self, kind: str, payload: dict) -> None:
        """Out-of-band event (summarize round progress etc.)."""
        with self._cond:
            self._append_locked(kind, dict(payload))

    # -- consumer side (HTTP handler thread) ------------------------------

    def pop(self, timeout_s: float,
            gen: int | None = None) -> tuple[str, dict, int] | None:
        """Next (kind, payload, seq), or None on an empty poll — the caller
        re-checks the request future and keeps draining. ``gen`` is the
        attachment token from :meth:`attach`; a superseded consumer gets
        :class:`StreamDetached` instead of stealing the new one's events."""
        self.last_consumed = time.monotonic()
        t_end = time.monotonic() + timeout_s
        with self._cond:
            while True:
                if gen is not None and gen != self._gen:
                    raise StreamDetached(self.request_id)
                if self._q:
                    return self._q.popleft()
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(timeout=remaining)

    def resume_snapshot(self) -> tuple[str, int]:
        """Atomically (text, seq) for a ``Last-Event-ID`` reconnect: the
        full emitted text so far, with buffered DELTA events dropped —
        their text is already inside the snapshot (push_text advances the
        high-water mark at push, not at pop), so replaying them after the
        snapshot would double bytes. Non-delta events (summarize progress)
        stay queued. Deltas pushed after this call are suffixes beyond the
        snapshot, so snapshot + subsequent deltas == final text — the
        resumed form of the concatenation identity."""
        with self._cond:
            self._q = deque(e for e in self._q if e[0] != "delta")
            return self._sent, self._seq

    def attach(self) -> int:
        """Claim the channel for a (re)connecting consumer; any previous
        consumer's pops raise StreamDetached from now on. Refreshes the
        idle clock, so a resume-in-time beats the disconnect sweep."""
        self.last_consumed = time.monotonic()
        with self._cond:
            self._gen += 1
            self._cond.notify_all()
            return self._gen

    def empty(self) -> bool:
        with self._lock:
            return not self._q

    @property
    def emitted_text(self) -> str:
        """The producer high-water mark — everything already emitted as
        deltas. A resume replays this as one ``snapshot`` event and then
        continues with live deltas (snapshot + subsequent deltas == the
        final text, the resumed form of the concatenation identity)."""
        with self._lock:
            return self._sent

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def idle_for(self) -> float:
        """Seconds since a consumer last popped (or attached) — the
        disconnect sweep's signal. Lock-free read by design."""
        return time.monotonic() - self.last_consumed

    def close(self) -> None:
        """Drop buffered events and make further pushes no-ops: called when
        the request is terminally resolved with no consumer left (cancel,
        disconnect past the resume window) so a dead stream costs nothing."""
        with self._cond:
            self._closed = True
            self._q.clear()
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed


class StreamRegistry:
    """Live streams by request id — the ``Last-Event-ID`` resume surface
    (serve/server.py). An entry outlives its HTTP handler on purpose: a
    disconnected client reconnects within the idle window and reattaches.
    Size is bounded two ways: terminal-and-drained entries are pruned on
    every register, and an LRU cap evicts the oldest beyond ``max_entries``
    (an evicted stream simply loses resumability, never correctness — the
    request itself is owned by the scheduler)."""

    def __init__(self, max_entries: int = 256) -> None:
        self.max_entries = max(int(max_entries), 1)
        # lock-order-sanitizer hook: HTTP handler threads only; never held
        # while taking any other serve lock except stream (attach/close)
        self._lock = make_lock("serve.streams")
        self._entries: dict[str, tuple] = {}  # rid -> (channel, future)

    def register(self, rid: str, channel: StreamChannel, future) -> None:
        with self._lock:
            self._prune_locked()
            self._entries[rid] = (channel, future)
            while len(self._entries) > self.max_entries:
                old_rid = next(iter(self._entries))
                self._entries.pop(old_rid)

    def _prune_locked(self) -> None:
        done = [
            rid for rid, (ch, fut) in self._entries.items()
            if fut.done() and (ch.closed or ch.empty())
        ]
        for rid in done:
            self._entries.pop(rid, None)

    def get(self, rid: str) -> tuple | None:
        with self._lock:
            return self._entries.get(rid)

    def unregister(self, rid: str) -> None:
        with self._lock:
            self._entries.pop(rid, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
