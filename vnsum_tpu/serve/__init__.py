"""Online serving layer over the TPU engine.

Every entry point before this package was offline: pipeline/runner.py submits
one big batch and waits, and the demo server handled one request at a time —
the exact serial-request shape the reference's Ollama loop had (PAPER.md §7).
This package is the missing online front-end for the batched engine:

- queue.py      bounded async request queue: per-request deadlines, typed
                429-style admission control (queue depth + token budget);
                requests carry their end-to-end trace_id and RequestTrace
                across the thread handoff
- scheduler.py  micro-batching scheduler thread that coalesces queued
                requests into shared engine batches (max-wait/max-batch
                policy), plus the QueuedBackend adapter that lets the
                existing strategies submit their rounds through the queue;
                installs the obs BatchTrace collector around each engine
                dispatch and derives per-request TTFT from its prefill end
- inflight.py   in-flight batching: slot-feeding scheduler over the
                backend's persistent decode loop (start_slot_loop) —
                finished rows are harvested and freed slots refilled from
                the queue at every segment boundary, TTFT anchored at each
                joiner's own prefill
- supervisor.py engine supervision: failure classification (transient /
                resource-exhausted / poison / fatal), bounded jittered
                retry with a per-request budget, batch bisection that
                quarantines poison requests, and the graceful-degradation
                ladder (shrink batch -> no spec -> no cache inserts ->
                typed 503 brownout, with recovery probes)
- journal.py    durability: write-ahead request journal (CRC-checked JSONL
                segments, group-commit fsync, atomic compaction) — every
                accepted request is journaled before engine work, outcomes
                append COMPLETE/typed-FAILED, and a restart replays the
                unfinished remainder byte-identically (--journal-dir)
- qos.py        multi-tenant QoS: tenant specs (--tenants), token-bucket
                rate quotas (typed 429 QUOTA + refill-derived Retry-After),
                and the deficit-round-robin weighted-fair pick the queue's
                take paths schedule with — interactive tier first, batch
                tier preemptible in in-flight mode
- stream.py     per-request SSE emit channel: the slot loop's harvest
                pushes decode-progress deltas at segment boundaries;
                concatenated deltas are byte-identical to the final text.
                BOUNDED: a slow consumer's pending events coalesce, and
                the StreamRegistry serves Last-Event-ID resumes off the
                channel's high-water snapshot. Cancellation rides the
                schedulers (DELETE /v1/requests/<id> + disconnect sweep):
                queued requests unwind their QoS bill, residents evict
                without requeue, and a typed CANCELLED terminal event
                rides the journal
- metrics.py    per-request + aggregate observability: counters, rolling
                gauges, and fixed-bucket histograms (queue wait / TTFT /
                e2e / occupancy / accepted-per-step) in Prometheus text,
                plus rolling windows (obs/window.py) feeding the SLO
                engine and per-tenant ledger; ONE metric registry, linted
                against the README table
- slo.py        declarative SLOs over the rolling windows (--slo):
                latency-quantile / error-rate / availability objectives,
                fast+slow burn rates, edge-triggered breaches that fire
                the flight recorder; /debug/slo + vnsum_serve_slo_* gauges
- usage.py      per-tenant usage ledger behind the capped
                TenantLabelRegistry (bounded metric cardinality): token/
                outcome counters + windowed latency per tenant, served at
                /v1/usage and as tenant-labeled series
- watchdog.py   liveness: heartbeat registry for every long-lived serving
                thread + a bounded-dispatch contract (token-derived
                wall-clock budget per engine dispatch). Stalls snapshot
                all thread stacks, classify (dispatch / lock / helper),
                and recover: hung-dispatch riders resolve typed
                RequestFailed(HUNG) or requeue via the journal's
                replayable ACCEPT (slot loops) with the scheduler thread
                replaced; lock/helper stalls escalate to a supervised
                journal-seal-and-exit
- server.py     stdlib HTTP front-end: /v1/summarize, /v1/generate,
                /healthz, /metrics, /v1/usage, /debug/trace, /debug/slo,
                /debug/flightrecorder, /debug/stacks
                (python -m vnsum_tpu.serve.server)

The engine itself is untouched: ONE scheduler thread owns all
backend.generate calls (TpuBackend's jit caches and stats are not
thread-safe), and concurrency lives entirely in front of it.
"""
from .queue import (
    RequestCancelled,
    RequestQueue,
    RequestShed,
    ServeRequest,
    ShedReason,
)
from .scheduler import MicroBatchScheduler, QueuedBackend
from .inflight import InflightScheduler
from .journal import JournalEntry, RequestJournal
from .metrics import ServeMetrics
from .qos import TenantSpec, TenantTable, TokenBucket, parse_tenant_specs
from .slo import Objective, SloEngine, parse_slo_spec
from .stream import StreamChannel, StreamDetached, StreamRegistry
from .usage import TenantLabelRegistry, UsageLedger
from .watchdog import WATCHDOG_EXIT_CODE, Watchdog, snapshot_stacks
from .supervisor import (
    EngineSupervisor,
    FailureClass,
    FatalEngineError,
    RequestFailed,
    RetryPolicy,
    Rung,
)

__all__ = [
    "EngineSupervisor",
    "FailureClass",
    "FatalEngineError",
    "InflightScheduler",
    "JournalEntry",
    "MicroBatchScheduler",
    "Objective",
    "RequestJournal",
    "QueuedBackend",
    "RequestCancelled",
    "RequestFailed",
    "RequestQueue",
    "RequestShed",
    "RetryPolicy",
    "Rung",
    "ServeMetrics",
    "ServeRequest",
    "ShedReason",
    "SloEngine",
    "StreamChannel",
    "StreamDetached",
    "StreamRegistry",
    "TenantLabelRegistry",
    "TenantSpec",
    "TenantTable",
    "TokenBucket",
    "UsageLedger",
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "parse_slo_spec",
    "parse_tenant_specs",
    "snapshot_stacks",
]
