"""Write-ahead request journal: crash-safe at-least-once serving.

PR 9 made the serving stack survive in-process failures; a process death
still silently lost every accepted-but-unfinished request. This module is
the durability layer: every :class:`~.queue.ServeRequest` admission writes
an ACCEPT record carrying the FULL request payload (prompt, decoding config
incl. seed, reference, cache hint, wall-clock deadline) before any engine
work happens, and the request's lifecycle appends START / COMPLETE / FAILED
transitions. On restart the journal is replayed: ACCEPTed-but-incomplete
requests re-enqueue through the normal supervised path (greedy decoding is
deterministic, so replays are byte-identical to an uninterrupted run),
COMPLETEd ones serve their recorded result to reconnecting clients
(``GET /v1/requests/<id>``), and the ledger invariant holds — every
journaled ACCEPT ends COMPLETE or typed FAILED, never lost
(scripts/chaos_soak.py SIGKILLs a live server at seeded points to prove it).

Storage format — append-only JSONL segments in one directory::

    journal.000001.jsonl        # sealed or compacted history
    journal.000002.jsonl        # the active segment (appends + fsync)

Each line is ``<crc32-hex8> <json>\\n`` with the CRC computed over the JSON
bytes: recovery verifies every record and drops a torn tail (the partial
line a kill mid-write leaves) instead of propagating garbage. Segments
rotate at ``max_segment_bytes``; on every reopen the whole journal is
COMPACTED — live state is rewritten into a fresh segment via write-temp +
``os.replace`` (crash-atomic: either the old segments or the complete new
one exist, never a half file) and the old segments are deleted, so the
journal's size is bounded by live state + one rotation window, not by
lifetime traffic.

Durability model, in order of what each write survives:

- ``write()+flush()`` per record -> survives **SIGKILL / process death**
  (the bytes are in the kernel page cache; only the machine losing power
  can drop them). This is the per-append cost — microseconds.
- batched ``fsync`` every ``fsync_interval_s`` (group commit, issued from
  the scheduler thread's appends, never the admission path) -> bounds the
  **power-loss** window without paying an fsync per request.
- ``seal()`` + compaction fsync + directory fsync -> clean-shutdown markers
  and renames are fully durable.

Threading: one internal lock (``make_lock("serve.journal")``); the queue
lock may be held while appending (the admission hook), so the journal lock
is always innermost — consistent with the lock-order sanitizer's graph.
"""
from __future__ import annotations

import json
import os
import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path

from ..analysis.sanitizers import make_lock
from ..core.artifacts import fsync_dir
from ..core.logging import get_logger
from ..obs.trace import emit
from ..testing.faults import fault

logger = get_logger("vnsum.serve.journal")

# record events; ACCEPT carries the replayable payload, COMPLETE the result
EV_ACCEPT = "accept"
EV_START = "start"
EV_COMPLETE = "complete"
EV_FAILED = "failed"
EV_SEAL = "seal"
# typed terminal cancellation (serve/scheduler.py cancel paths): the client
# withdrew the request (DELETE /v1/requests/<id>) or stopped listening
# (stream disconnect past the resume window). TERMINAL like COMPLETE/FAILED
# — compaction preserves it and restart replay never resurrects a cancelled
# request (the ledger invariant counts it as resolved, not lost)
EV_CANCELLED = "cancelled"
# QoS lifecycle (serve/qos.py + serve/inflight.py): PREEMPTED marks a
# batch-tier request evicted from its decode slot, REQUEUED its re-entry
# into the queue (both non-terminal — the ACCEPT payload stays replayable,
# so a crash anywhere in the preempt->requeue window still replays the
# request to exactly one terminal state); STREAMING marks a request whose
# first SSE delta left the server
EV_PREEMPT = "preempted"
EV_REQUEUE = "requeued"
EV_STREAM = "streaming"
# structured jobs (serve/gang.py): a GANG record is group METADATA, not a
# request lifecycle event — ``rid`` is the gang id and ``members`` lists
# (child_rid, phase) pairs admitted since the last flush, so restart replay
# reconstructs group membership (and the /v1/requests per-phase progress
# view) without inferring it from rid prefixes. A GANG record with
# ``partial: true`` marks the group degraded: a member failed typed POISON
# and the reduce proceeded without it
EV_GANG = "gang"

# the non-terminal lifecycle states compaction must preserve (a preempted
# entry that compacts to a bare ACCEPT would lie to GET /v1/requests/<id>)
_NONTERMINAL_STATES = (EV_START, EV_PREEMPT, EV_REQUEUE, EV_STREAM)

_SEGMENT_PREFIX = "journal."
_SEGMENT_SUFFIX = ".jsonl"


@dataclass
class JournalEntry:
    """In-memory state of one journaled request."""

    rid: str
    status: str = EV_ACCEPT  # accept -> start -> complete|failed
    payload: dict = field(default_factory=dict)
    text: str | None = None
    gen_tokens: int = 0
    reason: str = ""
    detail: str = ""

    @property
    def terminal(self) -> bool:
        return self.status in (EV_COMPLETE, EV_FAILED, EV_CANCELLED)

    def to_dict(self) -> dict:
        d = {"rid": self.rid, "status": self.status}
        if self.status == EV_COMPLETE:
            d["text"] = self.text
            d["generated_tokens"] = self.gen_tokens
        elif self.status in (EV_FAILED, EV_CANCELLED):
            d["reason"] = self.reason
            d["detail"] = self.detail
        return d


def _encode(record: dict) -> bytes:
    body = json.dumps(record, ensure_ascii=False,
                      separators=(",", ":")).encode("utf-8")
    return b"%08x " % zlib.crc32(body) + body + b"\n"


def _decode(line: bytes) -> dict | None:
    """One journal line -> record dict, or None when torn/corrupt (bad CRC,
    truncated, malformed JSON)."""
    if len(line) < 10 or line[8:9] != b" ":
        return None
    body = line[9:]
    try:
        if int(line[:8], 16) != zlib.crc32(body):
            return None
        return json.loads(body)
    # lint-allow[swallowed-exception]: returning None IS the answer — the caller counts the record as torn and stops trusting the segment
    except (ValueError, UnicodeDecodeError):
        return None


def request_payload(req) -> dict:
    """The replayable payload of a ServeRequest: everything submit() needs
    to reconstruct it byte-identically (greedy) after a restart. Monotonic
    deadlines don't survive a process, so the remaining budget is stored as
    a wall-clock instant."""
    import dataclasses

    cfg = None
    if req.config is not None:
        cfg = dataclasses.asdict(req.config)
        cfg["eos_ids"] = list(cfg.get("eos_ids") or ())
    deadline_unix = None
    if req.deadline is not None:
        deadline_unix = time.time() + (req.deadline - time.monotonic())
    payload = {
        "prompt": req.prompt,
        "max_new_tokens": req.max_new_tokens,
        "config": cfg,
        "reference": req.reference,
        "cache_hint": req.cache_hint,
        "trace_id": req.trace_id,
        "deadline_unix": deadline_unix,
    }
    # QoS class survives restart: a replayed batch-tier request must stay
    # evictable and keep billing its tenant (omitted when default so old
    # journals and the common single-tenant case stay byte-compatible)
    if req.tenant:
        payload["tenant"] = req.tenant
    if req.tier != "interactive":
        payload["tier"] = req.tier
    # structured-job membership survives restart: a replayed gang member
    # must rejoin its group (affinity pick, whole-gang preemption, per-phase
    # progress) instead of replaying as an unrelated request (omitted when
    # ungrouped so old journals stay byte-compatible)
    gang_id = getattr(req, "gang_id", "")
    if gang_id:
        payload["gang"] = gang_id
        if getattr(req, "gang_phase", ""):
            payload["gang_phase"] = req.gang_phase
    # router-journaled summarize requests carry the strategy name so a
    # handoff replays them through /v1/summarize, not /v1/generate; engine
    # ServeRequests have no such attribute and stay byte-compatible
    approach = getattr(req, "approach", None)
    if approach:
        payload["approach"] = approach
    return payload


class RequestJournal:
    """Append-only request ledger over JSONL segments in ``directory``.

    Opening recovers existing state (CRC-checked, torn tails dropped) and
    compacts it into a fresh segment; the instance then appends lifecycle
    records until :meth:`seal`/:meth:`close`. ``keep_terminal`` bounds the
    in-memory (and post-compaction) history of finished requests — the
    oldest terminal entries are evicted first, so a long-lived server's
    ledger holds recent history plus ALL unfinished work, never unbounded
    lifetime traffic.
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        fsync_interval_s: float = 0.05,
        max_segment_bytes: int = 4 << 20,
        keep_terminal: int = 4096,
    ) -> None:
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.fsync_interval_s = float(fsync_interval_s)
        self.max_segment_bytes = int(max_segment_bytes)
        self.keep_terminal = int(keep_terminal)
        # lock-order-sanitizer hook: the queue lock may be held while
        # acquiring this one (admission hook); this lock is always innermost
        self._lock = make_lock("serve.journal")
        self._entries: OrderedDict[str, JournalEntry] = OrderedDict()  # guarded by: _lock
        self._trace_counts: dict[str, int] = {}   # guarded by: _lock
        self._replayed: set[str] = set()          # guarded by: _lock
        self._file = None                         # guarded by: _lock
        self._seg_bytes = 0                       # guarded by: _lock
        self._last_sync = time.monotonic()        # guarded by: _lock
        self._closed = False                      # guarded by: _lock
        # monotone counters for /metrics (racy scrape reads are fine)
        self.records = 0
        self.appended_bytes = 0
        self.fsyncs = 0
        self.rotations = 0
        self.torn_records = 0
        self.replayed_total = 0
        self.replay_seconds = 0.0
        self.recovered_sealed = False

        state, seq, sealed, torn, gangs = _read_directory(self.directory)
        self._entries = state
        # structured-job group metadata (serve/gang.py), rebuilt from GANG
        # records at recovery: {gang_id: {"members": {rid: phase},
        # "partial": bool}}            # guarded by: _lock
        self._gangs = gangs
        # running count of terminal entries so completion-path eviction is
        # O(1) except when actually evicting     # guarded by: _lock
        self._terminal = sum(1 for e in state.values() if e.terminal)
        self.torn_records = torn
        self.recovered_sealed = sealed
        for rid in state:
            base, _, n = rid.partition("#")
            cur = self._trace_counts.get(base, 0)
            self._trace_counts[base] = max(cur, int(n) + 1 if n else 1)
        self._seq = seq + 1
        self._compact_locked()

    # -- segment plumbing (all *_locked run under self._lock) -------------

    def _segment_path(self, seq: int) -> Path:
        return self.directory / f"{_SEGMENT_PREFIX}{seq:06d}{_SEGMENT_SUFFIX}"

    def _open_segment_locked(self) -> None:
        path = self._segment_path(self._seq)
        self._file = open(path, "ab")
        self._seg_bytes = path.stat().st_size

    # durable
    def _compact_locked(self) -> None:
        """Rewrite live state into a fresh segment (write-temp + fsync +
        ``os.replace`` + directory fsync — crash-atomic), then delete the
        old segments and start appending to the compacted one."""
        self._evict_terminal_locked()
        old = _segment_paths(self.directory)
        path = self._segment_path(self._seq)
        tmp = path.with_suffix(path.suffix + ".tmp")
        with open(tmp, "wb") as f:
            for entry in self._entries.values():
                f.write(_encode({"e": EV_ACCEPT, "rid": entry.rid,
                                 **entry.payload}))
                if entry.status == EV_COMPLETE:
                    f.write(_encode({"e": EV_COMPLETE, "rid": entry.rid,
                                     "text": entry.text,
                                     "gen": entry.gen_tokens}))
                elif entry.status == EV_FAILED:
                    f.write(_encode({"e": EV_FAILED, "rid": entry.rid,
                                     "reason": entry.reason,
                                     "detail": entry.detail}))
                elif entry.status == EV_CANCELLED:
                    # compaction-safe: a cancelled entry must stay CANCELLED
                    # across reopens — compacting it to a bare ACCEPT would
                    # resurrect it at the next restart replay
                    f.write(_encode({"e": EV_CANCELLED, "rid": entry.rid,
                                     "reason": entry.reason}))
                elif entry.status in _NONTERMINAL_STATES:
                    # preserve mid-lifecycle state (start / preempted /
                    # requeued / streaming) so the poll surface stays
                    # honest across a compacting reopen; the entry still
                    # replays from its ACCEPT payload either way
                    f.write(_encode({"e": entry.status, "rid": entry.rid}))
            # structured-job metadata rides compaction too: a gang whose
            # members were all evicted has nothing left to describe — drop
            # it so gang metadata is bounded by live history like entries
            self._gangs = {
                gid: meta for gid, meta in self._gangs.items()
                if any(r in self._entries for r in meta["members"])
            }
            for gid, meta in self._gangs.items():
                rec = {"e": EV_GANG, "rid": gid,
                       "members": [[r, p] for r, p in meta["members"].items()]}
                if meta.get("partial"):
                    rec["partial"] = True
                f.write(_encode(rec))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(self.directory)
        for p in old:
            if p != path:
                p.unlink(missing_ok=True)
        self._open_segment_locked()

    def _rotate_locked(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        self._seq += 1
        self.rotations += 1
        self.fsyncs += 1
        self._last_sync = time.monotonic()
        self._open_segment_locked()

    def _append_locked(self, record: dict, allow_sync: bool) -> None:
        if self._closed:
            return
        raw = _encode(record)
        self._file.write(raw)
        # flush to the KERNEL on every record: this is what makes a SIGKILL
        # lose nothing — fsync below only narrows the power-loss window
        self._file.flush()
        self._seg_bytes += len(raw)
        self.records += 1
        self.appended_bytes += len(raw)
        if not allow_sync:
            # admission path (queue lock held): flush-to-kernel only — no
            # fsync and no rotation here; the next scheduler-thread append
            # settles both (the segment overshoots its bound by at most the
            # accepts that land between two lifecycle appends)
            return
        if self._seg_bytes >= self.max_segment_bytes:
            self._rotate_locked()
            return  # rotation just fsynced
        now = time.monotonic()
        if now - self._last_sync >= self.fsync_interval_s:
            # seeded injection point (vnsum_tpu.testing.faults, site
            # `journal.fsync`): a `hang` here wedges the scheduler thread
            # INSIDE the journal lock with no dispatch ticket armed — the
            # watchdog's lock-classified stall, which must escalate to
            # seal-and-exit (a replacement thread would deadlock on this
            # very lock). Free when disarmed
            fault("journal.fsync")
            os.fsync(self._file.fileno())
            self.fsyncs += 1
            self._last_sync = now
            emit("journal_sync", now, time.monotonic() - now)

    def _evict_terminal_locked(self) -> None:
        excess = self._terminal - self.keep_terminal
        if excess <= 0:
            return
        for rid in [r for r, e in self._entries.items() if e.terminal][:excess]:
            del self._entries[rid]
        self._terminal -= excess

    # -- lifecycle appends -------------------------------------------------

    def accept(self, req) -> str:
        """Journal one admitted ServeRequest; assigns and returns its
        journal id. Idempotent per id: a request re-submitted at replay
        carries its original ``journal_rid`` and is NOT journaled twice —
        the replay-idempotence property (replaying twice enqueues once
        rides on the caller checking :meth:`take_unfinished`).

        Runs under the queue lock (the admission hook), so this path never
        fsyncs — flush-to-kernel only; group commit happens on the
        scheduler thread's lifecycle appends."""
        with self._lock:
            rid = req.journal_rid
            if rid is not None and rid in self._entries:
                return rid
            if rid is None:
                base = req.trace_id
                n = self._trace_counts.get(base, 0)
                self._trace_counts[base] = n + 1
                rid = base if n == 0 else f"{base}#{n}"
                req.journal_rid = rid
            payload = request_payload(req)
            self._entries[rid] = JournalEntry(rid=rid, payload=payload)
            self._append_locked({"e": EV_ACCEPT, "rid": rid, **payload},
                                allow_sync=False)
            return rid

    def start(self, rid: str) -> None:
        with self._lock:
            entry = self._entries.get(rid)
            if entry is None or entry.terminal:
                return
            entry.status = EV_START
            self._append_locked({"e": EV_START, "rid": rid}, allow_sync=True)

    def _lifecycle_locked(self, rid: str, event: str) -> None:
        """One non-terminal lifecycle transition (preempted / requeued /
        streaming): status update + append, scheduler-thread paths only."""
        entry = self._entries.get(rid)
        if entry is None or entry.terminal:
            return
        entry.status = event
        self._append_locked({"e": event, "rid": rid}, allow_sync=True)

    def preempt(self, rid: str) -> None:
        """The typed PREEMPTED event: the request's slot was evicted for
        higher-priority work; its ACCEPT payload remains the replayable
        source of truth (a crash before the matching REQUEUE still replays
        it — the mid-preemption chaos kill point proves this)."""
        with self._lock:
            self._lifecycle_locked(rid, EV_PREEMPT)

    def requeue(self, rid: str) -> None:
        with self._lock:
            self._lifecycle_locked(rid, EV_REQUEUE)

    def streaming(self, rid: str) -> None:
        """First SSE delta left the server for this request."""
        with self._lock:
            self._lifecycle_locked(rid, EV_STREAM)

    def gang(self, gang_id: str, members: list[tuple[str, str]]) -> None:
        """Journal one structured-job membership flush (serve/gang.py):
        ``members`` is the (child_rid, phase) batch admitted since the last
        flush — one record per fan-out round, not per member, so a 40-chunk
        map round costs one append. Idempotent per member (replay-safe:
        a re-flushed member just overwrites its phase)."""
        if not members:
            return
        with self._lock:
            meta = self._gangs.setdefault(
                gang_id, {"members": {}, "partial": False}
            )
            meta["members"].update(members)
            self._append_locked(
                {"e": EV_GANG, "rid": gang_id,
                 "members": [[r, p] for r, p in members]},
                allow_sync=True,
            )

    def gang_partial(self, gang_id: str, reason: str = "poison") -> None:
        """Mark a gang DEGRADED: a member failed typed POISON and the reduce
        proceeded without its output. Journaled so a restarted server's
        /v1/requests view still distinguishes a degraded summary from a
        complete one. Idempotent."""
        with self._lock:
            meta = self._gangs.setdefault(
                gang_id, {"members": {}, "partial": False}
            )
            if meta["partial"]:
                return
            meta["partial"] = True
            self._append_locked(
                {"e": EV_GANG, "rid": gang_id, "partial": True,
                 "reason": reason},
                allow_sync=True,
            )

    def gang_info(self, gang_id: str) -> dict | None:
        """Group metadata for the poll surface: {"members": {rid: phase},
        "partial": bool} or None when the id never flushed a gang."""
        with self._lock:
            meta = self._gangs.get(gang_id)
            if meta is None:
                return None
            return {"members": dict(meta["members"]),
                    "partial": bool(meta["partial"])}

    def gangs_unfinished(self) -> dict[str, dict]:
        """Gangs with at least one non-terminal member — what startup
        replay hands the GangRegistry so replayed members rejoin their
        groups."""
        with self._lock:
            out = {}
            for gid, meta in self._gangs.items():
                live = any(
                    (e := self._entries.get(r)) is not None and not e.terminal
                    for r in meta["members"]
                )
                if live:
                    out[gid] = {"members": dict(meta["members"]),
                                "partial": bool(meta["partial"])}
            return out

    def complete(self, rid: str, text: str, gen_tokens: int = 0) -> None:
        with self._lock:
            entry = self._entries.get(rid)
            if entry is None or entry.terminal:
                return
            entry.status = EV_COMPLETE
            entry.text = text
            entry.gen_tokens = int(gen_tokens)
            self._terminal += 1
            self._append_locked(
                {"e": EV_COMPLETE, "rid": rid, "text": text,
                 "gen": int(gen_tokens)}, allow_sync=True,
            )
            self._evict_terminal_locked()

    def fail(self, rid: str, reason: str, detail: str = "") -> None:
        """Typed terminal failure — sheds and supervised give-ups both land
        here; the ledger invariant counts them as resolved, not lost."""
        with self._lock:
            entry = self._entries.get(rid)
            if entry is None or entry.terminal:
                return
            entry.status = EV_FAILED
            entry.reason = reason
            entry.detail = detail[:500]
            self._terminal += 1
            self._append_locked(
                {"e": EV_FAILED, "rid": rid, "reason": reason,
                 "detail": entry.detail}, allow_sync=True,
            )
            self._evict_terminal_locked()

    def cancel(self, rid: str, reason: str = "api") -> None:
        """Typed terminal CANCELLED — the client withdrew the request or
        stopped listening. Terminal like fail(): the ledger invariant
        counts it resolved, replay skips it, and (like every terminal
        append) it no-ops on an already-terminal entry, which is what makes
        DELETE idempotent against completion races."""
        with self._lock:
            entry = self._entries.get(rid)
            if entry is None or entry.terminal:
                return
            entry.status = EV_CANCELLED
            entry.reason = reason
            self._terminal += 1
            self._append_locked(
                {"e": EV_CANCELLED, "rid": rid, "reason": reason},
                allow_sync=True,
            )
            self._evict_terminal_locked()

    def sync(self) -> None:
        """Force the batched fsync now."""
        with self._lock:
            if self._file is not None and not self._closed:
                t0 = time.monotonic()
                fault("journal.fsync")
                os.fsync(self._file.fileno())
                self.fsyncs += 1
                self._last_sync = time.monotonic()
                emit("journal_sync", t0, self._last_sync - t0)

    def seal(self) -> None:
        """Clean-shutdown marker: append SEAL and fsync. A journal whose
        last record is SEAL recovered with zero unfinished entries came
        from a graceful drain."""
        with self._lock:
            if self._closed:
                return
            self._append_locked({"e": EV_SEAL, "t": time.time()},
                                allow_sync=False)
            os.fsync(self._file.fileno())
            self.fsyncs += 1

    def close(self) -> None:
        with self._lock:
            if self._file is not None and not self._closed:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
            self._closed = True

    # -- recovery / introspection -----------------------------------------

    def take_unfinished(self) -> list[JournalEntry]:
        """Entries accepted (or started) but not terminal, each returned AT
        MOST ONCE per process — the replay source. Marking them replayed
        in-memory is what makes calling replay twice enqueue once."""
        with self._lock:
            out = [
                e for e in self._entries.values()
                if not e.terminal and e.rid not in self._replayed
            ]
            self._replayed.update(e.rid for e in out)
            return out

    def note_replay(self, n: int, seconds: float) -> None:
        self.replayed_total += n
        self.replay_seconds += seconds

    def lookup(self, rid: str) -> list[JournalEntry]:
        """The poll surface (``GET /v1/requests/<id>``): the entry named
        ``rid`` plus any fan-out children ``rid#N``."""
        prefix = rid + "#"
        with self._lock:
            return [
                e for r, e in self._entries.items()
                if r == rid or r.startswith(prefix)
            ]

    def pending(self) -> int:
        with self._lock:
            return len(self._entries) - self._terminal

    def stats_dict(self) -> dict:
        """Scrape-time counters for /metrics (vnsum_serve_journal_*)."""
        return {
            "records": self.records,
            "appended_bytes": self.appended_bytes,
            "fsyncs": self.fsyncs,
            "rotations": self.rotations,
            "torn_records": self.torn_records,
            "replayed": self.replayed_total,
            "replay_seconds": round(self.replay_seconds, 6),
            "pending": self.pending(),
        }

    @staticmethod
    def read_state(directory: str | Path):
        """Read-only ledger view: (entries, sealed, torn_records) without
        opening the journal for writing or compacting — what the chaos-soak
        harness audits after the final shutdown."""
        entries, _seq, sealed, torn, _gangs = _read_directory(Path(directory))
        return entries, sealed, torn

    @staticmethod
    def read_gangs(directory: str | Path) -> dict[str, dict]:
        """Read-only structured-job view: {gang_id: {"members":
        {rid: phase}, "partial": bool}} — the chaos-soak gang audit's
        membership source (every admitted gang must fold to a terminal
        parent aggregate)."""
        _entries, _seq, _sealed, _torn, gangs = _read_directory(
            Path(directory)
        )
        return gangs


def aggregate_status(entries: list[JournalEntry]) -> str:
    """Fold one request's ledger entries (the id plus its ``#N`` fan-out
    children) into the ONE client-facing status — shared by
    ``GET /v1/requests/<id>`` and the ``DELETE`` cancel surface so the two
    can never disagree.

    Entries under one id are either RETRIES of one payload (same prompt —
    client re-submitted after a crash, at-least-once) or FAN-OUT siblings
    (different prompts). For retries any COMPLETE means the request
    succeeded, whatever a replayed duplicate did; for fan-out a failed
    child fails the request, and a cancelled child (with everyone else
    already terminal) marks the gang cancelled. Mid-lifecycle precedence
    (QoS + streaming states): any child actively on the engine
    (streaming > started) outranks one parked by preemption
    (requeued > preempted) — the aggregate answers "is anything moving",
    not "is everything moving"."""
    statuses = {e.status for e in entries}
    same_payload = len({e.payload.get("prompt") for e in entries}) == 1
    if same_payload and EV_COMPLETE in statuses:
        return "completed"
    if EV_FAILED in statuses:
        if (
            not same_payload
            and EV_COMPLETE in statuses
            and all(e.terminal for e in entries)
        ):
            # degraded fan-out (serve/gang.py): a member failed typed
            # POISON but the gang delivered a reduce over the survivors —
            # terminal, yet the client must be able to tell this summary
            # from a complete one. Gated on all-terminal: while siblings
            # are still moving the fold keeps reporting "failed" (the
            # pre-gang contract) and flips to "partial" only once the
            # degraded result actually exists
            return "partial"
        return "failed"
    if statuses == {EV_COMPLETE}:
        return "completed"
    if (
        EV_CANCELLED in statuses
        and statuses <= {EV_CANCELLED, EV_COMPLETE}
    ):
        # the gang is fully terminal with at least one cancel: the request
        # was withdrawn. A still-moving sibling falls through to the
        # mid-lifecycle states below instead (cancel is in flight)
        return "cancelled"
    if EV_STREAM in statuses:
        return "streaming"
    if EV_START in statuses or EV_COMPLETE in statuses:
        return "started"  # partial progress across fan-out
    if EV_REQUEUE in statuses:
        return "requeued"  # preempted, back in the queue
    if EV_PREEMPT in statuses:
        return "preempted"  # evicted, requeue not yet journaled
    return "accepted"


# -- directory scan ----------------------------------------------------------


def _segment_paths(directory: Path) -> list[Path]:
    out = []
    for p in directory.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}"):
        try:
            int(p.name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)])
        # lint-allow[swallowed-exception]: a non-numeric name simply is not a segment; skipping it is the resolution
        except ValueError:
            continue
        out.append(p)
    return sorted(out)


def _read_directory(directory: Path):
    """Replay every segment -> (entries, max_seq, sealed, torn_records,
    gangs).

    A record that fails CRC/decode stops the read of ITS segment (everything
    after an unverifiable record is untrusted), which covers the torn-tail
    case a kill mid-append leaves; earlier records and later segments are
    unaffected."""
    entries: OrderedDict[str, JournalEntry] = OrderedDict()
    gangs: dict[str, dict] = {}
    max_seq = 0
    sealed = False
    torn = 0
    for path in _segment_paths(directory):
        max_seq = max(
            max_seq,
            int(path.name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]),
        )
        data = path.read_bytes()
        for line in data.split(b"\n"):
            if not line:
                continue
            rec = _decode(line)
            if rec is None:
                torn += 1
                logger.warning(
                    "journal %s: dropping torn/corrupt record (and the "
                    "rest of the segment)", path.name,
                )
                break
            sealed = _apply(entries, rec, gangs)
    return entries, max_seq, sealed, torn, gangs


def _apply(entries: OrderedDict, rec: dict, gangs: dict | None = None) -> bool:
    """Fold one record into the state map; returns the new sealed flag
    (True only when THIS record is a seal — any later record unseals)."""
    ev = rec.get("e")
    if ev == EV_SEAL:
        return True
    rid = rec.get("rid")
    if not isinstance(rid, str):
        return False
    if ev == EV_GANG:
        if gangs is not None:
            meta = gangs.setdefault(rid, {"members": {}, "partial": False})
            for pair in rec.get("members") or []:
                if isinstance(pair, list) and len(pair) == 2:
                    meta["members"][str(pair[0])] = str(pair[1])
            if rec.get("partial"):
                meta["partial"] = True
        return False
    if ev == EV_ACCEPT:
        if rid not in entries:
            payload = {k: v for k, v in rec.items() if k not in ("e", "rid")}
            entries[rid] = JournalEntry(rid=rid, payload=payload)
    elif ev in _NONTERMINAL_STATES:
        entry = entries.get(rid)
        if entry is not None and not entry.terminal:
            entry.status = ev
    elif ev == EV_COMPLETE:
        entry = entries.get(rid)
        if entry is not None and not entry.terminal:
            entry.status = EV_COMPLETE
            entry.text = rec.get("text", "")
            entry.gen_tokens = int(rec.get("gen", 0))
    elif ev == EV_FAILED:
        entry = entries.get(rid)
        if entry is not None and not entry.terminal:
            entry.status = EV_FAILED
            entry.reason = str(rec.get("reason", "error"))
            entry.detail = str(rec.get("detail", ""))
    elif ev == EV_CANCELLED:
        entry = entries.get(rid)
        if entry is not None and not entry.terminal:
            entry.status = EV_CANCELLED
            entry.reason = str(rec.get("reason", "api"))
    return False


# -- read-only inspection CLI -------------------------------------------------


def _main(argv: list[str] | None = None) -> int:
    """``python -m vnsum_tpu.serve.journal <dir>``: dump a journal
    directory's ledger as JSON without opening it for writing — live /
    terminal counts plus every unfinished ACCEPT with its full replayable
    payload. The unfinished list is exactly what the router's
    journal-handoff failover re-dispatches onto survivors, so this is the
    handoff-debugging tool: point it at a dead worker's journal and see
    what is owed."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m vnsum_tpu.serve.journal",
        description="Read-only request-journal inspection (no writes, "
                    "no compaction).",
    )
    parser.add_argument("directory", help="journal directory to read")
    args = parser.parse_args(argv)
    directory = Path(args.directory)
    if not directory.is_dir():
        print(json.dumps({"error": f"not a directory: {directory}"}),
              file=sys.stderr)
        return 2
    entries, sealed, torn = RequestJournal.read_state(directory)
    by_status: dict[str, int] = {}
    unfinished = []
    for entry in entries.values():
        by_status[entry.status] = by_status.get(entry.status, 0) + 1
        if not entry.terminal:
            unfinished.append({"rid": entry.rid, "status": entry.status,
                               "payload": entry.payload})
    out = {
        "directory": str(directory),
        "sealed": sealed,
        "torn_records": torn,
        "entries": len(entries),
        "live": len(unfinished),
        "terminal": len(entries) - len(unfinished),
        "by_status": by_status,
        "unfinished_accepts": unfinished,
    }
    print(json.dumps(out, ensure_ascii=False, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())


