"""Engine-worker process lifecycle for the replica fleet.

A *worker* is the single-process server (serve/server.py) run behind the
front-door router (serve/router.py): a full engine with its own journal
subdirectory, its own ``/healthz`` + ``/readyz``, and the unchanged
``/v1/*`` surface — the fleet layer adds process topology, it does not
fork the protocol. This module provides the pieces that make a server a
*managed* worker:

- :func:`main` — ``python -m vnsum_tpu.serve.worker``: a thin wrapper
  over ``serve.server.main`` that names the process for logs and forwards
  every other flag unchanged, so the worker IS the server and the HTTP
  surface needs no second implementation.
- :class:`WorkerHandle` — spawn / readiness-probe / drain / restart
  control of ONE worker subprocess. Exit codes are part of the contract:
  ``0`` is a graceful drain + journal seal, ``WATCHDOG_EXIT_CODE`` (86)
  is the watchdog's seal-and-exit — both leave a replayable journal
  behind, which is exactly what the router's journal-handoff failover
  consumes. Anything else is a crash (so is SIGKILL), and the journal's
  torn-tail recovery covers those too.
- :func:`build_fleet` — N handles under one fleet directory, each with a
  per-worker journal subdir and an OS-assigned port.

Nothing here runs an engine in-process: the handle's whole job is being
the process-manager half of the drain-one-restart-one deploy story.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

from ..core.logging import get_logger
from ..testing.chaos import free_port, http_json
from .watchdog import WATCHDOG_EXIT_CODE

logger = get_logger("vnsum.serve.worker")


class WorkerHandle:
    """One engine-worker subprocess: spawn, probe, drain, restart.

    Single-threaded ownership contract: exactly one manager (the router's
    probe loop, a rolling-restart thread that has taken the worker out of
    rotation first, or a test) drives a handle at a time — the handle
    itself holds no lock.
    """

    def __init__(self, name: str, port: int, *, journal_dir: str,
                 host: str = "127.0.0.1",
                 extra_args: list[str] | None = None,
                 env: dict | None = None) -> None:
        self.name = name
        self.host = host
        self.port = port
        self.journal_dir = str(journal_dir)
        self.extra_args = list(extra_args or [])
        self.env = env
        self.proc: subprocess.Popen | None = None
        self.generation = 0  # bumped by every start() — deploy bookkeeping
        self.last_rc: int | None = None

    def argv(self) -> list[str]:
        return [
            sys.executable, "-m", "vnsum_tpu.serve.worker",
            "--name", self.name,
            "--host", self.host,
            "--port", str(self.port),
            "--journal-dir", self.journal_dir,
            *self.extra_args,
        ]

    def start(self) -> None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.env:
            env.update(self.env)
        self.generation += 1
        self.proc = subprocess.Popen(
            self.argv(), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        logger.info("spawned worker %s (pid %d, :%d, gen %d)",
                    self.name, self.proc.pid, self.port, self.generation)

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def poll(self) -> int | None:
        """Exit code if the process has died, else None (running or never
        started). Records the last observed code for deploy bookkeeping."""
        if self.proc is None:
            return None
        rc = self.proc.poll()
        if rc is not None:
            self.last_rc = rc
        return rc

    @property
    def sealed_exit(self) -> bool:
        """Did the last death look journal-sealed? (graceful drain or the
        watchdog's seal-and-exit — either way replay is clean, not torn)."""
        return self.last_rc in (0, WATCHDOG_EXIT_CODE)

    def wait_ready(self, timeout_s: float = 30.0) -> None:
        """Poll ``/readyz`` until 200 — the worker is routable (journal
        replay finished, not draining, not browned out)."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            rc = self.poll()
            if rc is not None:
                raise RuntimeError(
                    f"worker {self.name} exited during startup (rc={rc})"
                )
            try:
                status, _ = http_json("GET", self.host, self.port,
                                      "/readyz", timeout=2.0)
                if status == 200:
                    return
            # lint-allow[swallowed-exception]: a refused connect during bring-up is the expected state this loop polls through; the deadline below resolves a worker that never comes up
            except OSError:
                pass
            time.sleep(0.05)
        raise TimeoutError(
            f"worker {self.name} on :{self.port} never became ready"
        )

    def sigterm(self) -> None:
        if self.alive:
            self.proc.terminate()

    def sigkill(self) -> None:
        if self.alive:
            self.proc.kill()

    def wait_exit(self, timeout_s: float = 30.0) -> int:
        rc = self.proc.wait(timeout=timeout_s)
        self.last_rc = rc
        return rc

    def drain(self, timeout_s: float = 30.0) -> int:
        """The graceful half of drain-one-restart-one: SIGTERM (worker
        drains its queue, seals its journal) and wait. Escalates to
        SIGKILL only if the drain deadline passes — the journal makes even
        that safe, just not clean."""
        if not self.alive:
            return self.poll() if self.proc is not None else -1
        self.sigterm()
        try:
            return self.wait_exit(timeout_s)
        except subprocess.TimeoutExpired:
            logger.warning("worker %s ignored SIGTERM for %.1fs — killing",
                           self.name, timeout_s)
            self.sigkill()
            return self.wait_exit(10.0)


def build_fleet(n: int, fleet_dir: str, *,
                extra_args: list[str] | None = None,
                env: dict | None = None,
                host: str = "127.0.0.1") -> list[WorkerHandle]:
    """N worker handles under one fleet directory: ``<fleet>/<name>`` as
    each worker's journal subdir, OS-assigned ports. Handles are built,
    not started — the router starts them so a crash-looping worker is
    *its* probe loop's problem from the first breath."""
    handles = []
    for i in range(int(n)):
        name = f"worker-{i}"
        handles.append(WorkerHandle(
            name, free_port(),
            journal_dir=os.path.join(fleet_dir, name),
            host=host, extra_args=extra_args, env=env,
        ))
    return handles


def main(argv: list[str] | None = None) -> int:
    """``python -m vnsum_tpu.serve.worker``: name the process, then hand
    every remaining flag to ``serve.server.main`` unchanged."""
    import argparse

    parser = argparse.ArgumentParser(prog="vnsum-serve-worker",
                                     add_help=False)
    parser.add_argument("--name", default=None)
    args, rest = parser.parse_known_args(argv)
    name = args.name or f"worker-{os.getpid()}"
    logger.info("engine worker %s starting", name)
    from .server import main as server_main

    return server_main(rest)


if __name__ == "__main__":
    raise SystemExit(main())
