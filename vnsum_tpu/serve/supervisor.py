"""Engine supervision: failure classification, retry/bisect policy, and the
graceful-degradation ladder.

One engine exception used to fail every rider of the co-scheduled batch with
the stranger's error, and nothing distinguished "the TPU hiccuped" from "this
request deterministically crashes the engine" from "HBM is gone". The
supervisor gives the schedulers a policy object that does:

**Classification.** Every dispatch failure lands in one of four classes:

- ``TRANSIENT``  — default; retryable with backoff (device hiccup, dropped
  connection, a bug that might not reproduce).
- ``RESOURCE``   — allocation-shaped (message carries ``RESOURCE_EXHAUSTED``
  — what a jax OOM surfaces — or ``MemoryError``): retryable, AND evidence
  the current operating point is too hot, so repeated strikes step the
  degradation ladder down.
- ``POISON``     — deterministic input errors (the ``PERMANENT_ERRORS``
  family from core/faults.py): retrying is burning device time; the batch
  is bisected immediately to quarantine the culprit.
- ``FATAL``      — explicitly marked unrecoverable (``FatalEngineError`` or
  an exception with a truthy ``.fatal``): fail the whole group, typed.

**Retry budget + backoff.** Each request carries an ``attempts`` counter;
retries are capped per REQUEST (not per batch — a rider that keeps landing
in crashing batches eventually stops being retried) and spaced by bounded,
seeded-jitter exponential backoff. A group that exhausts its budget
collectively is bisected rather than failed — innocent riders escape through
the half that dispatches cleanly, and the poison request bottoms out alone,
failing with :class:`RequestFailed` (class POISON: it failed every attempt,
finally with no one else to blame).

**Degradation ladder.** Repeated RESOURCE strikes step down a config ladder;
each rung keeps the restrictions of the ones above it::

    0 HEALTHY          full configuration
    1 REDUCED_BATCH    engine dispatch width halved (batches and slot loops)
    2 NO_SPEC          speculative decoding off (drops the k+1-wide verify)
    3 NO_CACHE_INSERT  prefix-cache insertion off (stops pool churn; hits
                       still serve)
    4 BROWNOUT         new external admissions shed with a typed 503 +
                       Retry-After (internal fan-out of already-admitted
                       work still runs)

Recovery is probed, not assumed: after ``probe_interval_s`` without a
resource strike the ladder climbs one rung (evaluated on scheduler
successes AND at the admission gate, so a fully-browned-out server can heal
with no traffic dispatching).

Threading: classification and policy reads are pure/lock-free; ladder state
is mutated under a small internal lock because the admission gate (HTTP
threads, under the queue lock) probes recovery while the scheduler thread
records strikes. The queue lock is always acquired BEFORE this one, never
after — no cycle for the lock-order sanitizer.

The hot path stays supervised-but-free: a healthy dispatch costs one
``record_success()`` (a lock-free fast path when the ladder is at HEALTHY
and no strikes are pending) — no wrapping, no extra dispatches.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from enum import Enum, IntEnum

from ..analysis.sanitizers import make_lock
from ..core.faults import PERMANENT_ERRORS
from ..core.logging import get_logger

logger = get_logger("vnsum.serve.supervisor")


class FailureClass(str, Enum):
    TRANSIENT = "transient"
    RESOURCE = "resource_exhausted"
    POISON = "poison"
    FATAL = "fatal"
    # a dispatch that never RETURNED (serve/watchdog.py): declared past its
    # wall-clock budget, riders resolved typed without an exception ever
    # firing. Retryable from the client's seat (the request itself is not
    # implicated — re-submission rides the normal supervised path), and a
    # ladder strike from the server's (a host that hangs dispatches is a
    # host running too hot)
    HUNG = "hung"


class Rung(IntEnum):
    """Degradation ladder rungs; higher = more degraded. Each rung implies
    every restriction above it."""

    HEALTHY = 0
    REDUCED_BATCH = 1
    NO_SPEC = 2
    NO_CACHE_INSERT = 3
    BROWNOUT = 4


class FatalEngineError(RuntimeError):
    """Raise (or subclass) to mark a failure the supervisor must not retry
    or bisect — the engine itself is gone."""


class RequestFailed(RuntimeError):
    """Typed terminal failure delivered on a request future after
    supervision gave up: carries the :class:`FailureClass` and the last
    underlying error. ``RequestFailed(POISON)`` is the quarantine verdict —
    this request deterministically crashed its dispatches."""

    def __init__(self, failure_class: FailureClass, detail: str = "",
                 cause: BaseException | None = None) -> None:
        self.failure_class = failure_class
        self.cause = cause
        msg = f"request failed ({failure_class.value})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def classify_failure(e: BaseException) -> FailureClass:
    """Map an engine exception to its failure class. String-matching on
    RESOURCE_EXHAUSTED is deliberate: that is what a jax ``XlaRuntimeError``
    OOM carries, and depending on the jaxlib type would couple serving
    policy to a version-specific import."""
    if isinstance(e, FatalEngineError) or getattr(e, "fatal", False):
        return FailureClass.FATAL
    if isinstance(e, MemoryError) or "RESOURCE_EXHAUSTED" in str(e):
        return FailureClass.RESOURCE
    if isinstance(e, PERMANENT_ERRORS):
        return FailureClass.POISON
    return FailureClass.TRANSIENT


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry/backoff knobs. ``max_attempts`` counts FAILED dispatches
    a single request may be part of before it stops being retried;
    backoff(n) = min(base * 2^(n-1), max) * (1 + jitter * U[0,1)) with a
    seeded RNG so hermetic fault tests replay the exact same schedule."""

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.25
    seed: int = 0


class EngineSupervisor:
    def __init__(
        self,
        policy: RetryPolicy | None = None,
        *,
        resource_strikes_per_step: int = 2,
        probe_interval_s: float = 5.0,
        brownout_retry_after_s: float = 1.0,
        max_rung: Rung = Rung.BROWNOUT,
    ) -> None:
        self.policy = policy or RetryPolicy()
        self.resource_strikes_per_step = max(1, int(resource_strikes_per_step))
        self.probe_interval_s = float(probe_interval_s)
        self.brownout_retry_after_s = float(brownout_retry_after_s)
        self.max_rung = Rung(max_rung)
        self._rng = random.Random(self.policy.seed)
        # lock-order-sanitizer hook: plain threading.Lock in production.
        # Order contract: the queue lock may be held while acquiring this
        # one (admission_gate under submit), never the reverse
        self._lock = make_lock("serve.supervisor")
        # ladder state: MUTATED only under _lock; rung reads are deliberately
        # lock-free (an int read is atomic, and a stale rung for one dispatch
        # is harmless) — so no '# guarded by' annotation, by design
        self._rung = Rung.HEALTHY
        self._strikes = 0
        # recovery clock: restamped on every resource strike AND on every
        # rung transition; _maybe_recover climbs one rung per
        # probe_interval_s of silence on this clock
        self._last_change = 0.0
        # monotone counters for /metrics (scrape reads are racy ints)
        self.step_downs = 0
        self.recoveries = 0

    # -- classification / backoff (pure) ---------------------------------

    classify = staticmethod(classify_failure)

    def backoff_s(self, attempt: int) -> float:
        """Jittered exponential delay before retry number ``attempt``
        (1-based), capped at the policy maximum."""
        p = self.policy
        base = min(p.backoff_base_s * (2 ** max(attempt - 1, 0)),
                   p.backoff_max_s)
        return base * (1.0 + p.jitter * self._rng.random())

    # -- ladder ----------------------------------------------------------

    @property
    def rung(self) -> Rung:
        return self._rung

    def batch_limit(self, base: int) -> int:
        """Engine dispatch width under the current rung: halved from
        REDUCED_BATCH down."""
        if self._rung >= Rung.REDUCED_BATCH:
            return max(1, base // 2)
        return base

    @property
    def spec_enabled(self) -> bool:
        return self._rung < Rung.NO_SPEC

    @property
    def cache_inserts_enabled(self) -> bool:
        return self._rung < Rung.NO_CACHE_INSERT

    def admission_gate(self) -> float | None:
        """Brownout probe for the queue's admission check: Retry-After
        seconds when shedding, None when admitting. Also the recovery
        ticker — a browned-out server takes no batches, so the scheduler
        never runs record_success(); probing here lets the ladder climb on
        the next knock instead of never."""
        if self._rung is Rung.HEALTHY:
            return None
        self._maybe_recover()
        return (
            self.brownout_retry_after_s
            if self._rung >= Rung.BROWNOUT else None
        )

    def note_failure(self, cls: FailureClass) -> None:
        """Ladder bookkeeping for one classified dispatch failure; called
        from the scheduler thread. EVERY resource strike — sub-threshold
        and at-max-rung included — restamps the recovery clock: the probe
        interval measures quiet time since the last strike, not since the
        last rung change, so the ladder can't oscillate back up into an
        operating point that is still failing. HUNG counts as a resource
        strike (serve/watchdog.py): a wedged dispatch is the same
        too-hot-operating-point evidence an OOM is."""
        if cls not in (FailureClass.RESOURCE, FailureClass.HUNG):
            return
        with self._lock:
            self._last_change = time.monotonic()
            self._strikes += 1
            if self._strikes < self.resource_strikes_per_step:
                return
            self._strikes = 0
            if self._rung >= self.max_rung:
                return
            self._rung = Rung(self._rung + 1)
            self.step_downs += 1
        logger.warning("degradation ladder stepped DOWN to %s",
                       self._rung.name)

    def record_success(self) -> None:
        """One clean dispatch/segment: clears pending strikes and probes
        recovery. Free when healthy (single attribute read)."""
        if self._rung is Rung.HEALTHY and not self._strikes:
            return
        with self._lock:
            self._strikes = 0
        self._maybe_recover()

    def _maybe_recover(self) -> None:
        with self._lock:
            if self._rung is Rung.HEALTHY:
                return
            now = time.monotonic()
            if now - self._last_change < self.probe_interval_s:
                return
            self._rung = Rung(self._rung - 1)
            self._last_change = now
            self.recoveries += 1
        logger.info("degradation ladder recovered UP to %s", self._rung.name)
