"""Declarative SLOs over the rolling windows: burn rates, error budgets,
and the anomaly trigger for the flight recorder.

PR 3's observability answers "what happened since boot"; an operator paging
on a live server needs "are we inside our objectives RIGHT NOW, and how
fast are we burning the error budget". This module is that judgement layer,
built as the standard SRE multi-window construction:

**Objectives** come from one declarative spec string (the ``--slo`` flag)::

    --slo "ttft_p99=0.5,e2e_p99=30,error_rate=0.01,availability=0.999"

- ``<metric>_p<q>=<seconds>`` — a latency objective: quantile ``q`` of
  ``metric`` (ttft / e2e / queue_wait) must stay under the threshold.
  Internally that is a FRACTION contract — at most ``1-q`` of requests may
  exceed the threshold — judged from the windowed histogram's interpolated
  ``fraction_le`` (observations past the top bucket bound count as
  violations, conservatively).
- ``error_rate=<f>`` — at most fraction ``f`` of resolved requests may
  error (engine failures; sheds and cancels are not errors).
- ``availability=<f>`` — at least fraction ``f`` of terminal outcomes must
  be successful answers; errors AND sheds count against it (a 429/503 is
  unavailability from the caller's seat, typed or not).

**Burn rates.** For each objective, ``burn = observed_bad_fraction /
allowed_bad_fraction`` over a window: 1.0 means burning the error budget
exactly as fast as the SLO allots, 10 means the budget lasts a tenth of
the period. Each objective is evaluated over TWO windows — fast (~1m,
"is it on fire") and slow (~10m, "has it been on fire long enough to
matter") — and a **breach** requires both to exceed their thresholds
(``breach_fast_burn`` / ``breach_slow_burn``): the classic multi-window
alert that ignores one bad second at low traffic but fires within a fast
window of a real regression. Breaches are edge-triggered: the transition
into breach appends a typed ``slo_breach`` event to the flight recorder
and dumps it (`obs/recorder.py`), so the post-mortem ring is on disk
while the incident is still happening.

Empty windows are vacuously compliant (burn 0): an idle server is not
violating its latency SLO, it is serving nobody.

The engine is deliberately NOT coupled into the supervisor ladder: the
ladder reacts to engine failures with config changes, the SLO layer
JUDGES externally-visible service quality and surfaces it (/healthz
status line, ``/debug/slo``, ``vnsum_serve_slo_*`` gauges, recorder
dumps). An operator can page on it; the server does not self-mutate on it.

Threading: the whole evaluation (window reads + burn math + breach latch)
serializes under ``make_lock("serve.slo")`` so concurrent evaluators (the
monitor thread, scrape/probe handlers) can never revert the edge-triggered
latch with a staler view. The metrics lock is acquired INSIDE the slo lock
(slo -> metrics, acyclic: nothing acquires slo while holding metrics);
recorder dumps run on a throwaway daemon thread so no probe handler blocks
on fsync. A small daemon monitor thread re-evaluates every ``interval_s``
so breaches fire the recorder even when nobody scrapes.
"""
from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass

from ..analysis.sanitizers import make_lock
from ..core.logging import get_logger

logger = get_logger("vnsum.serve.slo")

# latency objective token: <metric>_p<digits>, e.g. ttft_p99, e2e_p999
_LATENCY_RE = re.compile(r"^(ttft|e2e|queue_wait)_p(\d{2,3})$")
_METRIC_KEYS = {
    "ttft": "ttft_seconds",
    "e2e": "e2e_seconds",
    "queue_wait": "queue_wait_seconds",
}


@dataclass(frozen=True)
class Objective:
    """One parsed objective. ``allowed`` is the bad-outcome fraction the
    SLO budget allots (1-q for latency quantiles, f for error_rate,
    1-f for availability) — the denominator of every burn rate."""

    name: str
    kind: str            # "latency" | "error_rate" | "availability"
    threshold: float     # latency seconds / error fraction / availability
    allowed: float
    metric: str = ""     # windowed-histogram key (latency kinds only)


def parse_slo_spec(text: str) -> dict[str, Objective]:
    """``name=value`` entries, comma-separated, into objectives — the
    ``--slo`` CLI surface. Unknown names, malformed values, and degenerate
    targets (p100, error_rate >= 1, availability of 0) raise ValueError."""
    out: dict[str, Objective] = {}
    for part in [p.strip() for p in text.split(",") if p.strip()]:
        name, sep, raw = part.partition("=")
        name = name.strip()
        if not sep:
            raise ValueError(f"SLO entry {part!r}: want name=value")
        if name in out:
            raise ValueError(f"duplicate SLO objective {name!r}")
        try:
            value = float(raw)
        except ValueError:
            raise ValueError(f"SLO {name!r}: bad value {raw!r}") from None
        m = _LATENCY_RE.match(name)
        if m:
            digits = m.group(2)
            if digits == "100":
                # p100 would silently parse as 100/1000 = p10; a 100th
                # percentile has no error budget anyway — reject loudly
                raise ValueError(
                    f"SLO {name!r}: p100 is degenerate (no error budget); "
                    "use p99/p999"
                )
            q = int(digits) / (10 ** len(digits))
            if not 0.0 < q < 1.0:
                raise ValueError(f"SLO {name!r}: quantile must be in (0,1)")
            if value <= 0:
                raise ValueError(f"SLO {name!r}: threshold must be > 0s")
            out[name] = Objective(name=name, kind="latency", threshold=value,
                                  allowed=1.0 - q,
                                  metric=_METRIC_KEYS[m.group(1)])
        elif name == "error_rate":
            if not 0.0 < value < 1.0:
                raise ValueError("SLO error_rate must be in (0,1)")
            out[name] = Objective(name=name, kind="error_rate",
                                  threshold=value, allowed=value)
        elif name == "availability":
            if not 0.0 < value < 1.0:
                raise ValueError("SLO availability must be in (0,1)")
            out[name] = Objective(name=name, kind="availability",
                                  threshold=value, allowed=1.0 - value)
        else:
            raise ValueError(
                f"unknown SLO objective {name!r} (want "
                "ttft_pNN/e2e_pNN/queue_wait_pNN/error_rate/availability)"
            )
    if not out:
        raise ValueError("empty --slo spec")
    return out


class SloEngine:
    """Evaluates objectives against the metrics' rolling windows."""

    def __init__(
        self,
        objectives: dict[str, Objective],
        metrics,
        *,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        breach_fast_burn: float = 10.0,
        breach_slow_burn: float = 1.0,
        recorder=None,
        interval_s: float = 1.0,
        heartbeat=None,
    ) -> None:
        if not objectives:
            raise ValueError("SloEngine needs at least one objective")
        if fast_window_s >= slow_window_s:
            raise ValueError("fast window must be shorter than slow window")
        self.objectives = dict(objectives)
        self.metrics = metrics
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.breach_fast_burn = float(breach_fast_burn)
        self.breach_slow_burn = float(breach_slow_burn)
        self.recorder = recorder
        # lock-order-sanitizer hook: plain threading.Lock in production.
        # Held across the whole evaluation, metrics reads included (the
        # slo -> metrics edge; see the module docstring's race rationale)
        self._lock = make_lock("serve.slo")
        self._breached: set[str] = set()   # guarded by: _lock
        self.breaches_total = 0            # monotone; racy reads fine
        self._last_breach: dict | None = None  # guarded by: _lock
        # watchdog liveness stamp (serve/watchdog.py): a helper-kind
        # Heartbeat the monitor loop beats once per evaluation tick, so a
        # wedged evaluation (stuck metrics lock) is detected and escalated
        # instead of silently stopping SLO judgement. None = unmonitored
        self.heartbeat = heartbeat
        self._stop = threading.Event()
        self._thread = None
        if interval_s and interval_s > 0:
            self._interval_s = float(interval_s)
            self._thread = threading.Thread(
                target=self._monitor, name="vnsum-serve-slo", daemon=True
            )
            self._thread.start()

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _bad_fraction(obj: Objective, view: dict) -> float:
        if obj.kind == "latency":
            return 1.0 - view["hists"][obj.metric].fraction_le(obj.threshold)
        counts = view["counts"]
        completed = counts.get("completed", 0)
        errors = counts.get("errors", 0)
        if obj.kind == "error_rate":
            denom = completed + errors
            return errors / denom if denom else 0.0
        # availability: sheds count against it too
        shed = counts.get("shed", 0)
        denom = completed + errors + shed
        return (errors + shed) / denom if denom else 0.0

    @staticmethod
    def _exemplar(obj: Objective, view: dict) -> str | None:
        """A recent trace_id from a VIOLATING bucket of the objective's
        window (latency objectives only) — the /debug/trace breadcrumb the
        breach report carries."""
        if obj.kind != "latency":
            return None
        h = view["hists"][obj.metric]
        exemplars = view["exemplars"][obj.metric]
        # buckets wholly above the threshold, worst (most recent by bucket
        # recency) first; fall back to the topmost populated exemplar
        start = h.bucket_index(obj.threshold)
        best: tuple | None = None
        for idx in range(len(exemplars) - 1, start - 1, -1):
            ex = exemplars[idx]
            if ex is not None and ex[1] > obj.threshold:
                if best is None or ex[2] > best[2]:
                    best = ex
        return best[0] if best is not None else None

    def evaluate(self, now: float | None = None) -> dict:
        """One full evaluation: per-objective compliance/burn/budget over
        both windows, breach edge-detection (fires the recorder), and the
        export dict every surface (gauges, /debug/slo, /healthz) renders
        from. Returns {"objectives": {}, "windowed": False} when the
        metrics were built without rolling windows.

        The WHOLE evaluation — window reads included — runs under the
        engine lock: evaluators race in from the monitor thread and every
        scrape/probe handler, and a thread holding a STALER window view
        must never overwrite a fresher thread's breach latch (that would
        re-detect one sustained breach as a second transition and
        double-fire the recorder). Serializing reads-plus-latch makes the
        latch monotone in view time. The serve.slo -> serve.metrics edge
        this adds is acyclic (nothing acquires slo under the metrics
        lock); recorder I/O still happens after release."""
        with self._lock:
            if now is None:
                # ONE moment for both views: a sub-window boundary falling
                # between the two reads would give fast and slow different
                # window sets and could fire the breach latch on skew
                now = self.metrics.now()
            fast = self.metrics.window_view(self.fast_window_s, now)
            slow = self.metrics.window_view(self.slow_window_s, now)
            if fast is None or slow is None:
                return {"objectives": {}, "breached": False,
                        "breaches_total": self.breaches_total,
                        "windowed": False}
            objectives: dict[str, dict] = {}
            now_breached: set[str] = set()
            for name, obj in self.objectives.items():
                bad_fast = self._bad_fraction(obj, fast)
                bad_slow = self._bad_fraction(obj, slow)
                burn_fast = bad_fast / obj.allowed
                burn_slow = bad_slow / obj.allowed
                breaching = (burn_fast >= self.breach_fast_burn
                             and burn_slow >= self.breach_slow_burn)
                if breaching:
                    now_breached.add(name)
                entry = {
                    "kind": obj.kind,
                    "target": obj.threshold,
                    "allowed_bad_fraction": obj.allowed,
                    "compliance": 1.0 - bad_fast,
                    "burn_fast": burn_fast,
                    "burn_slow": burn_slow,
                    "budget_remaining": max(0.0, 1.0 - burn_slow),
                    "breaching": breaching,
                }
                ex = self._exemplar(obj, fast)
                if ex is not None:
                    entry["exemplar_trace_id"] = ex
                objectives[name] = entry
            new = now_breached - self._breached
            self._breached = now_breached
            if new:
                self.breaches_total += len(new)
                self._last_breach = {
                    "t_wall": time.time(),
                    "objectives": sorted(new),
                    "detail": {n: objectives[n] for n in sorted(new)},
                }
            last_breach = self._last_breach
        for name in sorted(new):
            o = objectives[name]
            logger.warning(
                "SLO breach: %s burn fast=%.2f slow=%.2f (thresholds "
                "%.2f/%.2f)", name, o["burn_fast"], o["burn_slow"],
                self.breach_fast_burn, self.breach_slow_burn,
            )
            if self.recorder is not None:
                self.recorder.record(
                    "slo_breach", rid=o.get("exemplar_trace_id", ""),
                    objective=name,
                    burn_fast=round(o["burn_fast"], 3),
                    burn_slow=round(o["burn_slow"], 3),
                )
        if new and self.recorder is not None:
            # sustained fast burn IS the anomaly: snapshot the ring while
            # the incident's lead-up is still in it. Off-thread: evaluate()
            # also runs inline in /healthz and /metrics handlers, and a
            # liveness probe must never block on a dump's fsync (the dump
            # is throttled and thread-safe; a daemon thread per breach
            # transition is rare by construction)
            threading.Thread(
                target=self.recorder.dump, args=("slo_fast_burn",),
                name="vnsum-slo-dump", daemon=True,
            ).start()
        return {
            "objectives": objectives,
            "breached": bool(now_breached),
            "breaches_total": self.breaches_total,
            "last_breach": last_breach,
            "windowed": True,
        }

    # -- surfaces ----------------------------------------------------------

    def export_state(self, now: float | None = None) -> dict:
        """The scrape-time payload for the vnsum_serve_slo_* gauges —
        evaluation is cheap (merging a handful of 13-bucket histograms),
        so every scrape judges fresh state rather than a cached verdict."""
        return self.evaluate(now)

    def debug_payload(self) -> dict:
        """``GET /debug/slo``: full objective detail + engine config."""
        state = self.evaluate()
        return {
            "config": {
                "objectives": {
                    name: {"kind": o.kind, "target": o.threshold,
                           "allowed_bad_fraction": o.allowed}
                    for name, o in self.objectives.items()
                },
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "breach_fast_burn": self.breach_fast_burn,
                "breach_slow_burn": self.breach_slow_burn,
            },
            **state,
        }

    def status_line(self) -> str:
        """The one-line /healthz summary: worst burning objective, or the
        minimum budget remaining when everything is inside budget."""
        state = self.evaluate()
        objectives = state["objectives"]
        if not objectives:
            return "no rolling windows (windowed metrics disabled)"
        if state["breached"]:
            # worst among the objectives actually BREACHING — a non-breaching
            # objective can carry the highest fast burn (slow threshold
            # unmet) and must not displace the real page
            worst = max(
                (n for n in objectives if objectives[n]["breaching"]),
                key=lambda n: objectives[n]["burn_fast"],
            )
            o = objectives[worst]
            return (f"BREACH {worst}: burn fast={o['burn_fast']:.1f} "
                    f"slow={o['burn_slow']:.1f}")
        budget = min(o["budget_remaining"] for o in objectives.values())
        return (f"ok ({len(objectives)} objectives, "
                f"budget remaining >= {budget:.3f})")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- monitor thread ----------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                if self.heartbeat is not None:
                    self.heartbeat.beat()
                self.evaluate()
            # lint-allow[swallowed-exception]: the monitor is an alerting sidecar — an evaluation bug must not kill it (the next tick retries) and there is no request to resolve
            except Exception:
                logger.exception("SLO evaluation failed; continuing")
