"""Fleet observability federation: one scrape loop, one rollup surface,
one incident bundle.

The router (serve/router.py) already owns routing truth — which worker is
up, where each rid went, when a failover fired. What it could NOT answer
before this module is the fleet-wide observability questions: "what is the
fleet's p99 right now", "which worker is burning the error budget", and
"give me everything every process knows about the last 60 seconds in ONE
artifact". Scraping N workers from Prometheus answers the first at 15s
granularity and the other two never.

Three pieces, all router-side (workers stay dumb — they just answer
``GET /debug/obs/snapshot`` and ``POST /debug/dump``):

- :class:`FleetFederation` — a daemon scrape loop pulling each worker's
  JSON snapshot on a cadence. Counters sum into ``vnsum_serve_fleet_*``
  rollups, histograms merge bucket-for-bucket through
  ``Histogram.merge_from`` (mismatched ladders are a typed
  ``HistogramMergeError``, counted and skipped, never mis-binned), and
  per-worker gauges keep the ``worker=`` label — bounded by the roster
  registry, enforced by the ``metric-label-cardinality`` lint. The same
  samples feed the fleet ``/debug/slo`` + ``/v1/usage`` views and carry
  each worker's **clock offset**, estimated from the scrape's RTT midpoint
  (``worker_mono - (t_send + t_recv)/2``) — the correction that lets
  ``/debug/trace`` stitch worker spans onto the router's clock.

- :class:`IncidentManager` — turns an anomaly moment (fleet SLO fast-burn,
  a mark-down, a failover, an operator SIGUSR1) into ONE on-disk bundle:
  it mints an incident id, snapshots the router's routing-decision ring,
  fans ``POST /debug/dump?incident=<id>`` out to every worker (each
  contributes its flight-recorder ring + thread stacks), and writes a
  manifest with every process's clock anchors. Throttled per trigger
  reason like the flight recorder's dumps — a flapping worker produces one
  bundle, not a disk full.

- :func:`fold_incident_bundle` — the causal-ordering half the report CLI
  (scripts/incident_report.py) and the chaos soak's validator share: every
  event in a bundle maps onto wall time via its process's own anchor
  (``started_wall + t_rel``), so the merged timeline is monotone without
  any cross-process clock agreement beyond NTP-grade wall clocks.

Locks: ``serve.federation`` guards the sample table (never held across a
worker round trip — scrape I/O runs bare, results land under the lock);
``serve.incident`` guards only the throttle/counter state. Both are leaf
locks below ``serve.router`` in the sanitizer's order.
"""
from __future__ import annotations

import itertools
import threading
import time
from pathlib import Path

from ..analysis.sanitizers import make_lock
from ..core.artifacts import atomic_write_json
from ..core.logging import get_logger
from ..obs.histogram import Histogram, HistogramMergeError, SCRAPE_BUCKETS_S
from .metrics import _METRICS, _PREFIX

logger = get_logger("vnsum.serve.federation")

# the typed incident trigger vocabulary (the fleet_incidents_total label
# set): fleet SLO fast-burn, a worker mark-down, a journal-handoff
# failover, and the operator's SIGUSR1
INCIDENT_REASONS = ("slo_fast_burn", "markdown", "failover", "operator")

_incident_seq = itertools.count(1)


class WorkerSample:
    """One scrape result: the worker's snapshot plus the router-side
    stamps that date it and align its clock."""

    __slots__ = ("name", "payload", "t_mono", "scrape_s", "clock_offset_s",
                 "error")

    def __init__(self, name: str, payload: dict | None, t_mono: float,
                 scrape_s: float, clock_offset_s: float,
                 error: str | None = None) -> None:
        self.name = name
        self.payload = payload          # /debug/obs/snapshot JSON (or None)
        self.t_mono = t_mono            # router monotonic at receive
        self.scrape_s = scrape_s        # round-trip seconds
        self.clock_offset_s = clock_offset_s  # worker mono -> router mono
        self.error = error

    def age_s(self) -> float:
        return time.monotonic() - self.t_mono


class FleetFederation:
    """Scrape loop + rollup state over a RouterState's worker table."""

    def __init__(self, state, *, interval_s: float = 1.0,
                 stale_after_s: float | None = None,
                 fast_burn_cb=None) -> None:
        self.state = state
        self.interval_s = max(float(interval_s), 0.02)
        # a sample older than this no longer steers markdown decisions or
        # counts toward fleet SLO verdicts (default: two missed scrapes)
        self.stale_after_s = (
            float(stale_after_s) if stale_after_s is not None
            else 2.0 * self.interval_s + 0.5
        )
        # called (once per sweep, with a detail string) when any fresh
        # worker sample reports a breaching SLO — the router wires this to
        # IncidentManager.trigger("slo_fast_burn"); throttling lives there
        self.fast_burn_cb = fast_burn_cb
        # leaf lock: guards the sample table and counters, never held
        # across worker I/O
        self._lock = make_lock("serve.federation")
        self._samples: dict[str, WorkerSample] = {}  # guarded by: _lock
        self._scrapes: dict[str, int] = {}           # guarded by: _lock
        self._errors: dict[str, int] = {}            # guarded by: _lock
        self._merge_errors = 0                       # guarded by: _lock
        self._scrape_hist = Histogram(SCRAPE_BUCKETS_S)  # guarded by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="router-federation", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.scrape_all()

    # -- scraping ----------------------------------------------------------

    def scrape_all(self) -> None:
        """One sweep over the roster (also callable synchronously — the
        /debug/trace stitcher pulls a fresh sweep so just-finished worker
        spans make the merged trace)."""
        for w in list(self.state.workers):
            self.scrape_one(w)
        if self.fast_burn_cb is not None:
            burning = [
                (name, s.payload["slo"]["burn_fast_max"])
                for name, s in self.samples().items()
                if s.payload is not None
                and s.age_s() <= self.stale_after_s
                and (s.payload.get("slo") or {}).get("breached")
            ]
            if burning:
                self.fast_burn_cb(
                    "fleet SLO fast-burn: " + ", ".join(
                        f"{n} burn={b:.1f}" for n, b in sorted(burning)
                    )
                )

    def scrape_one(self, w) -> WorkerSample:
        """Pull one worker's snapshot; the RTT midpoint of this very round
        trip estimates the worker's monotonic-clock offset."""
        t0 = time.monotonic()
        payload, err = None, None
        try:
            status, body = self.state._worker_http(
                w, "GET", "/debug/obs/snapshot",
                timeout=self.state.probe_timeout_s,
            )
            if status == 200 and isinstance(body, dict):
                payload = body
            else:
                err = f"http:{status}"
        # lint-allow[swallowed-exception]: a refused scrape becomes the sample's error field and the staleness gauge — the fleet view degrades, nothing strands
        except OSError as e:
            err = str(e) or e.__class__.__name__
        t1 = time.monotonic()
        if payload is not None:
            # the worker stamped mono_now somewhere inside [t0, t1] on OUR
            # clock; the midpoint is the minimum-variance estimate, off by
            # at most RTT/2 — microseconds-to-milliseconds on loopback,
            # far below the span durations being aligned
            offset = float(payload.get("mono_now", 0.0)) - (t0 + t1) / 2.0
        else:
            prev = self.sample(w.name)
            offset = prev.clock_offset_s if prev is not None else 0.0
        sample = WorkerSample(w.name, payload, t1, t1 - t0, offset, err)
        with self._lock:
            self._scrapes[w.name] = self._scrapes.get(w.name, 0) + 1
            if err is not None:
                self._errors[w.name] = self._errors.get(w.name, 0) + 1
                # keep the previous good payload (staleness gauges show
                # its age) rather than blanking the fleet view on one
                # refused connection
                prev = self._samples.get(w.name)
                if prev is not None and prev.payload is not None:
                    prev.error = err
                    self._scrape_hist.observe(t1 - t0)
                    return prev
            self._samples[w.name] = sample
            self._scrape_hist.observe(t1 - t0)
        return sample

    # -- sample access -----------------------------------------------------

    def sample(self, name: str) -> WorkerSample | None:
        with self._lock:
            return self._samples.get(name)

    def samples(self) -> dict[str, WorkerSample]:
        with self._lock:
            return dict(self._samples)

    def fresh_payload(self, name: str) -> dict | None:
        """The worker's snapshot if recent enough to act on (the probe
        loop's federation-fed markdown policy), else None."""
        s = self.sample(name)
        if s is None or s.payload is None or s.age_s() > self.stale_after_s:
            return None
        return s.payload

    # -- rollups -----------------------------------------------------------

    def fleet_rollup(self) -> dict:
        """Counters summed, histograms merged, gauges kept per-worker —
        the aggregation-kind discipline: a summed gauge or an averaged
        histogram would lie."""
        counters: dict[str, int] = {}
        hists: dict[str, Histogram] = {}
        per_worker: dict[str, dict] = {}
        merge_errors = 0
        for name, s in sorted(self.samples().items()):
            if s.payload is None:
                per_worker[name] = {"stale": True, "age_s": round(s.age_s(), 3)}
                continue
            p = s.payload
            for k, v in (p.get("counters") or {}).items():
                counters[k] = counters.get(k, 0) + int(v)
            for k, st in (p.get("hists") or {}).items():
                try:
                    h = Histogram.from_state(st)
                    if k in hists:
                        hists[k].merge_from(h)
                    else:
                        hists[k] = h
                # lint-allow[swallowed-exception]: counted into merge_errors and logged — the rollup proceeds without the skewed worker's buckets, which IS the resolution
                except HistogramMergeError as e:
                    # a worker on a different ladder (version skew mid
                    # rolling-restart): skip its contribution, count it,
                    # never mis-bin — the typed error is the contract
                    merge_errors += 1
                    logger.warning("fleet histogram merge skipped for "
                                   "%s/%s: %s", name, k, e)
            row: dict = {
                "stale": s.age_s() > self.stale_after_s,
                "age_s": round(s.age_s(), 3),
                "clock_offset_s": round(s.clock_offset_s, 6),
                "ready": bool(p.get("ready")),
                "readyz_reason": p.get("readyz_reason", ""),
                "queue_depth": int(p.get("queue_depth", 0)),
            }
            if "degraded_rung" in p:
                row["degraded_rung"] = int(p["degraded_rung"])
            if "slo" in p:
                row["slo_breached"] = bool(p["slo"].get("breached"))
                row["slo_burn_fast_max"] = float(
                    p["slo"].get("burn_fast_max", 0.0)
                )
            if "watchdog" in p:
                row["watchdog_max_heartbeat_age_s"] = float(
                    p["watchdog"].get("max_heartbeat_age_s", 0.0)
                )
            per_worker[name] = row
        if merge_errors:
            with self._lock:
                self._merge_errors += merge_errors
        return {"counters": counters, "hists": hists,
                "per_worker": per_worker}

    def fleet_slo(self) -> dict:
        """The fleet ``/debug/slo`` view: every worker's objective table
        side by side, plus the per-worker burn attribution the "which
        replica is eating the budget" question needs."""
        workers: dict[str, dict] = {}
        attribution = []
        breached = False
        burn_fast_max = 0.0
        for name, s in sorted(self.samples().items()):
            if s.payload is None:
                workers[name] = {"stale": True}
                continue
            slo = s.payload.get("slo")
            if slo is None:
                workers[name] = {"slo": None,
                                 "stale": s.age_s() > self.stale_after_s}
                continue
            stale = s.age_s() > self.stale_after_s
            workers[name] = {**slo, "stale": stale}
            if not stale:
                breached = breached or bool(slo.get("breached"))
                burn = float(slo.get("burn_fast_max", 0.0))
                burn_fast_max = max(burn_fast_max, burn)
                attribution.append({"worker": name, "burn_fast_max": burn,
                                    "breached": bool(slo.get("breached"))})
        attribution.sort(key=lambda r: -r["burn_fast_max"])
        return {
            "role": "router",
            "breached": breached,
            "burn_fast_max": round(burn_fast_max, 4),
            "burn_attribution": attribution,
            "workers": workers,
        }

    def fleet_usage(self) -> dict:
        """The fleet ``/v1/usage`` view: per-tenant counters summed across
        workers; latency quantiles reported as the worst (max) worker
        quantile — quantiles do not sum, and for an SLO consumer the
        conservative bound is the honest merge without shipping every
        bucket ladder per tenant."""
        tenants: dict[str, dict] = {}
        per_worker: dict[str, dict] = {}
        window_s = None
        for name, s in sorted(self.samples().items()):
            if s.payload is None or "usage" not in s.payload:
                continue
            window_s = s.payload.get("usage_window_s", window_s)
            per_worker[name] = s.payload["usage"]
            for tenant, row in s.payload["usage"].items():
                agg = tenants.setdefault(tenant, {})
                for k, v in row.items():
                    if isinstance(v, dict):  # queue_wait / ttft / e2e
                        sub = agg.setdefault(k, {"count": 0})
                        sub["count"] += int(v.get("count", 0))
                        for q in ("p50_s", "p95_s", "p99_s"):
                            sub[q] = round(
                                max(sub.get(q, 0.0), float(v.get(q, 0.0))),
                                6,
                            )
                    else:
                        agg[k] = agg.get(k, 0) + int(v)
        return {"role": "router", "window_s": window_s,
                "tenants": tenants, "workers": per_worker}

    # -- trace stitching ---------------------------------------------------

    def trace_groups(self) -> list[dict]:
        """Per-worker groups for obs.export.merged_chrome_trace, clock
        offsets applied. Fan-out child rids (``base#N``) normalize to the
        base trace id so every hop of one client request — including the
        pre- and post-failover worker halves — lands in one merged
        process."""
        groups = []
        for name, s in sorted(self.samples().items()):
            if s.payload is None:
                continue
            traces = []
            for t in s.payload.get("traces") or []:
                base = str(t.get("trace_id", "")).partition("#")[0]
                if base != t.get("trace_id"):
                    t = {**t, "trace_id": base}
                traces.append(t)
            if traces:
                groups.append({"source": name,
                               "clock_offset_s": s.clock_offset_s,
                               "traces": traces})
        return groups

    # -- metrics -----------------------------------------------------------

    def metrics_lines(self, registry) -> list[str]:
        """vnsum_serve_federation_* + vnsum_serve_fleet_* text-format
        lines for the router's /metrics. ``registry`` is the router's
        bounded worker-roster TenantLabelRegistry — every ``worker=``
        label value passes through ``registry.canonical`` (the
        metric-label-cardinality contract for fleet series)."""
        rollup = self.fleet_rollup()
        with self._lock:
            scrapes = dict(self._scrapes)
            errors = dict(self._errors)
            scrape_hist = self._scrape_hist.copy()
        samples = self.samples()
        lines: list[str] = []

        def meta(name: str) -> None:
            typ, help_ = _METRICS[name]  # KeyError = unregistered metric
            lines.append(f"# HELP {_PREFIX}{name} {help_}")
            lines.append(f"# TYPE {_PREFIX}{name} {typ}")

        def worker_rows(name: str, rows) -> None:
            meta(name)
            for wname, value in rows:
                # worker= values pass through the roster registry — the
                # metric-label-cardinality rule requires the canonical()
                # call inline for fleet worker labels
                lines.append(
                    f'{_PREFIX}{name}'
                    f'{{worker="{registry.canonical(wname, touch=False)}"}}'
                    f" {value}"
                )

        worker_rows("federation_scrapes_total", sorted(scrapes.items()))
        worker_rows("federation_scrape_errors_total",
                    sorted(errors.items()))
        worker_rows("federation_staleness_seconds",
                    [(n, round(s.age_s(), 3))
                     for n, s in sorted(samples.items())])
        worker_rows("federation_clock_offset_seconds",
                    [(n, round(s.clock_offset_s, 6))
                     for n, s in sorted(samples.items())])
        typ, help_ = _METRICS["federation_scrape_seconds"]
        lines.extend(scrape_hist.render(
            f"{_PREFIX}federation_scrape_seconds", help_
        ))
        meta("fleet_requests_total")
        lines.append(f"{_PREFIX}fleet_requests_total "
                     f"{rollup['counters'].get('requests_total', 0)}")
        meta("fleet_requests_completed_total")
        lines.append(
            f"{_PREFIX}fleet_requests_completed_total "
            f"{rollup['counters'].get('requests_completed_total', 0)}"
        )
        meta("fleet_requests_errored_total")
        lines.append(
            f"{_PREFIX}fleet_requests_errored_total "
            f"{rollup['counters'].get('requests_errored_total', 0)}"
        )
        meta("fleet_generated_tokens_total")
        lines.append(
            f"{_PREFIX}fleet_generated_tokens_total "
            f"{rollup['counters'].get('generated_tokens_total', 0)}"
        )
        for hist_name in ("fleet_e2e_seconds", "fleet_ttft_seconds"):
            h = rollup["hists"].get(hist_name[len("fleet_"):])
            if h is not None:
                typ, help_ = _METRICS[hist_name]
                lines.extend(h.render(f"{_PREFIX}{hist_name}", help_))
        per_worker = rollup["per_worker"]

        def gauge_rows(name: str, key) -> None:
            rows = [
                (n, row[key]) for n, row in sorted(per_worker.items())
                if key in row
            ]
            if rows:
                worker_rows(name, rows)

        # up = fresh AND ready: a stale sample means the scrape loop has
        # lost sight of the worker, which for a fleet dashboard is down
        worker_rows("fleet_worker_up", [
            (n, 1 if (row.get("ready") and not row.get("stale")) else 0)
            for n, row in sorted(per_worker.items())
        ])
        gauge_rows("fleet_queue_depth", "queue_depth")
        gauge_rows("fleet_degraded_rung", "degraded_rung")
        gauge_rows("fleet_slo_burn_fast", "slo_burn_fast_max")
        rows = [
            (n, 1 if row.get("slo_breached") else 0)
            for n, row in sorted(per_worker.items())
            if "slo_breached" in row
        ]
        if rows:
            worker_rows("fleet_slo_breached", rows)
        return lines

    def stats_dict(self) -> dict:
        with self._lock:
            return {
                "scrapes": sum(self._scrapes.values()),
                "errors": sum(self._errors.values()),
                "merge_errors": self._merge_errors,
                "workers_sampled": len(self._samples),
            }


class IncidentManager:
    """Mints incident ids and collects one correlated bundle per trigger.

    A bundle directory (``<incident_dir>/<incident_id>/``) holds:
    ``manifest.json`` (trigger, wall time, per-process clock anchors),
    ``router.json`` (the router's routing-decision flight-recorder ring +
    health snapshot), and one ``worker_<name>.json`` per reachable worker
    (its ring + thread stacks, via ``POST /debug/dump?incident=``).
    """

    def __init__(self, state, federation: FleetFederation | None,
                 directory: str | Path | None, *,
                 min_interval_s: float = 30.0) -> None:
        self.state = state
        self.federation = federation
        self.directory = Path(directory) if directory else None
        self.min_interval_s = float(min_interval_s)
        # leaf lock: throttle stamps + counters only — capture I/O runs
        # on its own thread, never under any lock
        self._lock = make_lock("serve.incident")
        self._last: dict[str, float] = {}   # reason -> mono  # guarded by: _lock
        self.counts: dict[str, int] = {}    # reason -> fired  # guarded by: _lock

    def trigger(self, reason: str, detail: str = "",
                sync: bool = False) -> str | None:
        """Mint + capture an incident for ``reason`` (throttled per
        reason). Returns the incident id, or None when disabled or
        throttled. ``sync=True`` captures on the calling thread (tests,
        the SIGUSR1 handler's thread)."""
        if self.directory is None:
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return None
            self._last[reason] = now
            self.counts[reason] = self.counts.get(reason, 0) + 1
        incident = (f"inc_{int(time.time() * 1000)}"
                    f"_{next(_incident_seq):03d}")
        recorder = getattr(self.state, "recorder", None)
        if recorder is not None:
            recorder.record("incident", incident=incident, reason=reason,
                            detail=detail)
        logger.warning("incident %s minted (%s): %s", incident, reason,
                       detail or "-")
        if sync:
            self._capture(incident, reason, detail)
        else:
            threading.Thread(
                target=self._capture, args=(incident, reason, detail),
                name=f"incident-{incident}", daemon=True,
            ).start()
        return incident

    def _capture(self, incident: str, reason: str, detail: str) -> None:
        bundle = self.directory / incident
        try:
            bundle.mkdir(parents=True, exist_ok=True)
        # lint-allow[swallowed-exception]: an unwritable incident dir must not crash the capture thread — logged, and workers' own --flight-dir dumps still fire
        except OSError:
            logger.exception("incident %s: bundle dir %s", incident, bundle)
            return
        state = self.state
        manifest: dict = {
            "incident": incident,
            "reason": reason,
            "detail": detail,
            "wall": time.time(),
            "router": {
                "started_wall": state.started_wall,
                "mono_now": time.monotonic(),
            },
            "workers": {},
        }
        router_doc: dict = {"source": "router",
                            "health": state.health_payload()}
        recorder = getattr(state, "recorder", None)
        if recorder is not None:
            router_doc["flightrecorder"] = recorder.snapshot()
        collected = 0
        for w in list(state.workers):
            entry: dict = {"host": w.host, "port": w.port}
            if self.federation is not None:
                s = self.federation.sample(w.name)
                if s is not None:
                    entry["clock_offset_s"] = round(s.clock_offset_s, 6)
            try:
                status, body = state._worker_http(
                    w, "POST", f"/debug/dump?incident={incident}",
                    body={}, timeout=state.probe_timeout_s,
                )
            # lint-allow[swallowed-exception]: an unreachable worker (often the very process whose death minted the incident) lands in the manifest as an error entry — the bundle records the absence
            except OSError as e:
                entry["error"] = str(e) or e.__class__.__name__
                manifest["workers"][w.name] = entry
                continue
            if status == 200 and isinstance(body, dict):
                entry["file"] = f"worker_{w.name}.json"
                atomic_write_json(bundle / entry["file"],
                                  {"source": w.name, **body})
                collected += 1
            else:
                entry["error"] = f"http:{status}"
            manifest["workers"][w.name] = entry
        manifest["workers_collected"] = collected
        atomic_write_json(bundle / "router.json", router_doc)
        atomic_write_json(bundle / "manifest.json", manifest)
        logger.warning("incident %s: bundle at %s (%d/%d worker(s))",
                       incident, bundle, collected, len(state.workers))

    def counts_snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counts)


# -- bundle folding (shared with scripts/incident_report.py) ------------------


def fold_incident_bundle(bundle_dir: str | Path) -> dict:
    """Load one incident bundle and fold every process's flight-recorder
    ring into a single causally-ordered timeline.

    Each ring's events carry ``t_rel`` seconds since that PROCESS started
    plus the ring's ``started_wall`` anchor — so each event maps onto wall
    time with only its own process's anchors, and the merged sort is
    monotone by construction. Returns ``{"incident", "reason", "wall",
    "sources", "events": [{"wall", "source", "kind", ...}]}``.
    """
    import json

    bundle = Path(bundle_dir)
    manifest = json.loads((bundle / "manifest.json").read_text())
    events: list[dict] = []
    sources: dict[str, dict] = {}

    def fold_ring(source: str, doc: dict) -> None:
        ring = doc.get("flightrecorder")
        if not ring:
            sources[source] = {"events": 0}
            return
        anchor = float(ring.get("started_wall", 0.0))
        n = 0
        for e in ring.get("events", []):
            events.append({
                "wall": round(anchor + float(e.get("t_rel", 0.0)), 6),
                "source": source,
                **{k: v for k, v in e.items() if k != "t_rel"},
            })
            n += 1
        sources[source] = {"events": n, "started_wall": anchor,
                           "dropped": ring.get("events_dropped", 0)}

    router_file = bundle / "router.json"
    if router_file.exists():
        fold_ring("router", json.loads(router_file.read_text()))
    for name, entry in sorted((manifest.get("workers") or {}).items()):
        f = entry.get("file")
        if not f:
            continue
        path = bundle / f
        if path.exists():
            fold_ring(name, json.loads(path.read_text()))
    events.sort(key=lambda e: (e["wall"], e["source"], e.get("seq", 0)))
    return {
        "incident": manifest.get("incident"),
        "reason": manifest.get("reason"),
        "detail": manifest.get("detail", ""),
        "wall": manifest.get("wall"),
        "sources": sources,
        "events": events,
    }
