// vnsum_native — C++ host-side text core exposed over a C ABI (ctypes).
//
// The reference has no native code at all (SURVEY.md §2); this library takes
// the host-side hot loops off the single pipeline CPU so it can keep feeding
// the TPU: ROUGE-1/2/L scoring (tokenize + NLTK-mode Porter stemmer + O(n*m)
// LCS — the dominant host cost of the evaluation pass,
// evaluate/evaluate_summaries_semantic.py:561-575) and the recursive
// byte-budget text splitter used by the engine's default tokenizer.
//
// Semantics mirror vnsum_tpu/eval/rouge.py and vnsum_tpu/text/splitter.py
// exactly; tests fuzz both against the Python implementations.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- stemmer

// consonant test matching nltk: y is a consonant at 0, else a consonant iff
// the previous char is a vowel (i.e. not consonant(prev))
bool is_cons(const std::string& w, int i) {
    char c = w[i];
    if (c == 'a' || c == 'e' || c == 'i' || c == 'o' || c == 'u') return false;
    if (c == 'y') return i == 0 ? true : !is_cons(w, i - 1);
    return true;
}

int measure(const std::string& stem) {
    int m = 0;
    bool prev_v = false;
    for (int i = 0; i < (int)stem.size(); ++i) {
        bool v = !is_cons(stem, i);
        if (!v && prev_v) ++m;  // count v->c transitions
        prev_v = v;
    }
    return m;
}

bool has_vowel(const std::string& s) {
    for (int i = 0; i < (int)s.size(); ++i)
        if (!is_cons(s, i)) return true;
    return false;
}

bool ends_double_cons(const std::string& w) {
    int n = w.size();
    return n >= 2 && w[n - 1] == w[n - 2] && is_cons(w, n - 1);
}

bool ends_cvc(const std::string& w) {
    int n = w.size();
    if (n >= 3 && is_cons(w, n - 3) && !is_cons(w, n - 2) && is_cons(w, n - 1)) {
        char c = w[n - 1];
        if (c != 'w' && c != 'x' && c != 'y') return true;
    }
    // NLTK extension: 2-letter vc counts
    return n == 2 && !is_cons(w, 0) && is_cons(w, 1);
}

bool ends_with(const std::string& w, const char* suf) {
    size_t l = std::strlen(suf);
    return w.size() >= l && w.compare(w.size() - l, l, suf) == 0;
}

struct Rule {
    const char* suffix;
    const char* repl;
    int cond;  // 0: none, 1: m>0, 2: m>1, 3: m>1 && stem ends s/t
};

// first matching suffix wins; failed condition stops the step
std::string apply_rules(const std::string& w, const Rule* rules, int n) {
    for (int r = 0; r < n; ++r) {
        if (!ends_with(w, rules[r].suffix)) continue;
        std::string stem = w.substr(0, w.size() - std::strlen(rules[r].suffix));
        bool ok = true;
        switch (rules[r].cond) {
            case 1: ok = measure(stem) > 0; break;
            case 2: ok = measure(stem) > 1; break;
            case 3:
                ok = measure(stem) > 1 && !stem.empty() &&
                     (stem.back() == 's' || stem.back() == 't');
                break;
        }
        return ok ? stem + rules[r].repl : w;
    }
    return w;
}

std::string step1a(const std::string& w) {
    if (ends_with(w, "ies") && w.size() == 4) return w.substr(0, 1) + "ie";
    static const Rule rules[] = {
        {"sses", "ss", 0}, {"ies", "i", 0}, {"ss", "ss", 0}, {"s", "", 0}};
    return apply_rules(w, rules, 4);
}

std::string step1b(const std::string& w) {
    if (ends_with(w, "ied"))
        return w.substr(0, w.size() - 3) + (w.size() == 4 ? "ie" : "i");
    if (ends_with(w, "eed")) {
        std::string stem = w.substr(0, w.size() - 3);
        return measure(stem) > 0 ? stem + "ee" : w;
    }
    std::string inter;
    bool matched = false;
    if (ends_with(w, "ed")) {
        std::string stem = w.substr(0, w.size() - 2);
        if (has_vowel(stem)) { inter = stem; matched = true; }
    } else if (ends_with(w, "ing")) {
        std::string stem = w.substr(0, w.size() - 3);
        if (has_vowel(stem)) { inter = stem; matched = true; }
    }
    if (!matched) return w;
    if (ends_with(inter, "at") || ends_with(inter, "bl") || ends_with(inter, "iz"))
        return inter + "e";
    if (ends_double_cons(inter)) {
        char last = inter.back();
        if (last != 'l' && last != 's' && last != 'z')
            return inter.substr(0, inter.size() - 1);
        return inter;  // condition failed on matched *d rule -> stop
    }
    if (measure(inter) == 1 && ends_cvc(inter)) return inter + "e";
    return inter;
}

std::string step1c(const std::string& w) {
    if (!ends_with(w, "y")) return w;
    std::string stem = w.substr(0, w.size() - 1);
    if (stem.size() > 1 && is_cons(stem, stem.size() - 1)) return stem + "i";
    return w;
}

std::string step2(const std::string& w) {
    if (ends_with(w, "alli")) {
        std::string stem = w.substr(0, w.size() - 4);
        if (measure(stem) > 0) return step2(stem + "al");
    }
    static const Rule rules[] = {
        {"ational", "ate", 1}, {"tional", "tion", 1}, {"enci", "ence", 1},
        {"anci", "ance", 1},   {"izer", "ize", 1},    {"bli", "ble", 1},
        {"alli", "al", 1},     {"entli", "ent", 1},   {"eli", "e", 1},
        {"ousli", "ous", 1},   {"ization", "ize", 1}, {"ation", "ate", 1},
        {"ator", "ate", 1},    {"alism", "al", 1},    {"iveness", "ive", 1},
        {"fulness", "ful", 1}, {"ousness", "ous", 1}, {"aliti", "al", 1},
        {"iviti", "ive", 1},   {"biliti", "ble", 1},  {"fulli", "ful", 1}};
    for (const Rule& r : rules) {
        if (!ends_with(w, r.suffix)) continue;
        std::string stem = w.substr(0, w.size() - std::strlen(r.suffix));
        return measure(stem) > 0 ? stem + r.repl : w;
    }
    if (ends_with(w, "logi")) {
        // condition is on word minus "ogi" (the 'l' stays with the stem)
        std::string stem_l = w.substr(0, w.size() - 3);
        if (measure(stem_l) > 0) return w.substr(0, w.size() - 4) + "log";
        return w;
    }
    return w;
}

std::string step3(const std::string& w) {
    static const Rule rules[] = {
        {"icate", "ic", 1}, {"ative", "", 1}, {"alize", "al", 1},
        {"iciti", "ic", 1}, {"ical", "ic", 1}, {"ful", "", 1},
        {"ness", "", 1}};
    return apply_rules(w, rules, 7);
}

std::string step4(const std::string& w) {
    static const Rule rules[] = {
        {"al", "", 2},   {"ance", "", 2}, {"ence", "", 2}, {"er", "", 2},
        {"ic", "", 2},   {"able", "", 2}, {"ible", "", 2}, {"ant", "", 2},
        {"ement", "", 2}, {"ment", "", 2}, {"ent", "", 2}, {"ion", "", 3},
        {"ou", "", 2},   {"ism", "", 2},  {"ate", "", 2},  {"iti", "", 2},
        {"ous", "", 2},  {"ive", "", 2},  {"ize", "", 2}};
    return apply_rules(w, rules, 19);
}

std::string step5a(const std::string& w) {
    if (!ends_with(w, "e")) return w;
    std::string stem = w.substr(0, w.size() - 1);
    int m = measure(stem);
    if (m > 1) return stem;
    if (m == 1 && !ends_cvc(stem)) return stem;
    return w;
}

std::string step5b(const std::string& w) {
    if (ends_with(w, "ll") && measure(w.substr(0, w.size() - 1)) > 1)
        return w.substr(0, w.size() - 1);
    return w;
}

std::string porter_stem(const std::string& word) {
    static const std::unordered_map<std::string, std::string> irregular = {
        {"skies", "sky"},     {"sky", "sky"},       {"dying", "die"},
        {"lying", "lie"},     {"tying", "tie"},     {"news", "news"},
        {"innings", "inning"}, {"inning", "inning"}, {"outings", "outing"},
        {"outing", "outing"}, {"cannings", "canning"}, {"canning", "canning"},
        {"howe", "howe"},     {"proceed", "proceed"}, {"exceed", "exceed"},
        {"succeed", "succeed"}};
    auto it = irregular.find(word);
    if (it != irregular.end()) return it->second;
    if (word.size() <= 2) return word;
    std::string w = word;
    w = step1a(w);
    w = step1b(w);
    w = step1c(w);
    w = step2(w);
    w = step3(w);
    w = step4(w);
    w = step5a(w);
    w = step5b(w);
    return w;
}

// ------------------------------------------------------------- tokenizer

// rouge_score tokenization: lowercase, non-[a-z0-9] bytes are separators,
// stem tokens longer than 3 chars
std::vector<std::string> rouge_tokenize(const char* text, bool use_stemmer) {
    std::vector<std::string> out;
    std::string cur;
    for (const unsigned char* p = (const unsigned char*)text; *p; ++p) {
        unsigned char c = *p;
        if (c >= 'A' && c <= 'Z') c = c - 'A' + 'a';
        if ((c >= 'a' && c <= 'z') || (c >= '0' && c <= '9')) {
            cur.push_back((char)c);
        } else if (!cur.empty()) {
            out.push_back(std::move(cur));
            cur.clear();
        }
    }
    if (!cur.empty()) out.push_back(std::move(cur));
    if (use_stemmer) {
        for (auto& t : out)
            if (t.size() > 3) t = porter_stem(t);
    }
    return out;
}

// ----------------------------------------------------------------- rouge

using TokenIds = std::vector<int>;

TokenIds intern(const std::vector<std::string>& toks,
                std::unordered_map<std::string, int>& vocab) {
    TokenIds ids;
    ids.reserve(toks.size());
    for (const auto& t : toks) {
        auto it = vocab.find(t);
        if (it == vocab.end()) it = vocab.emplace(t, (int)vocab.size()).first;
        ids.push_back(it->second);
    }
    return ids;
}

void score_ngrams(const TokenIds& target, const TokenIds& pred, int n,
                  double* p, double* r, double* f) {
    std::unordered_map<uint64_t, int> t_counts, p_counts;
    auto key = [](const TokenIds& v, size_t i, int n) {
        uint64_t h = 1469598103934665603ull;
        for (int j = 0; j < n; ++j) {
            h ^= (uint64_t)(v[i + j] + 1);
            h *= 1099511628211ull;
        }
        return h;
    };
    for (size_t i = 0; i + n <= target.size(); ++i) ++t_counts[key(target, i, n)];
    for (size_t i = 0; i + n <= pred.size(); ++i) ++p_counts[key(pred, i, n)];
    long overlap = 0, t_total = 0, p_total = 0;
    for (auto& kv : t_counts) {
        t_total += kv.second;
        auto it = p_counts.find(kv.first);
        if (it != p_counts.end()) overlap += std::min(kv.second, it->second);
    }
    for (auto& kv : p_counts) p_total += kv.second;
    *p = p_total ? (double)overlap / p_total : 0.0;
    *r = t_total ? (double)overlap / t_total : 0.0;
    *f = (*p + *r) ? 2 * (*p) * (*r) / (*p + *r) : 0.0;
}

int lcs_len(const TokenIds& a, const TokenIds& b) {
    if (a.empty() || b.empty()) return 0;
    std::vector<int> prev(b.size() + 1, 0), cur(b.size() + 1, 0);
    for (size_t i = 1; i <= a.size(); ++i) {
        int ai = a[i - 1];
        for (size_t j = 1; j <= b.size(); ++j) {
            cur[j] = (ai == b[j - 1]) ? prev[j - 1] + 1
                                      : std::max(prev[j], cur[j - 1]);
        }
        std::swap(prev, cur);
    }
    return prev[b.size()];
}

}  // namespace

extern "C" {

// out9 = [p1, r1, f1, p2, r2, f2, pL, rL, fL]
void vn_rouge_score(const char* target, const char* prediction,
                    int use_stemmer, double* out9) {
    std::unordered_map<std::string, int> vocab;
    TokenIds t = intern(rouge_tokenize(target, use_stemmer), vocab);
    TokenIds p = intern(rouge_tokenize(prediction, use_stemmer), vocab);
    score_ngrams(t, p, 1, &out9[0], &out9[1], &out9[2]);
    score_ngrams(t, p, 2, &out9[3], &out9[4], &out9[5]);
    if (t.empty() || p.empty()) {
        out9[6] = out9[7] = out9[8] = 0.0;
    } else {
        int l = lcs_len(t, p);
        double pr = (double)l / p.size();
        double rc = (double)l / t.size();
        out9[6] = pr;
        out9[7] = rc;
        out9[8] = (pr + rc) ? 2 * pr * rc / (pr + rc) : 0.0;
    }
}

void vn_rouge_corpus(const char** targets, const char** preds, int n,
                     int use_stemmer, double* out /* n*9 */) {
    for (int i = 0; i < n; ++i)
        vn_rouge_score(targets[i], preds[i], use_stemmer, out + 9 * i);
}

// stem one word (ASCII, already lowercased); returns length written
int vn_porter_stem(const char* word, char* out, int out_cap) {
    std::string s = porter_stem(word);
    int n = std::min((int)s.size(), out_cap - 1);
    std::memcpy(out, s.data(), n);
    out[n] = '\0';
    return n;
}

int vn_count_words(const char* text) {
    int count = 0;
    bool in_word = false;
    for (const unsigned char* p = (const unsigned char*)text; *p; ++p) {
        // match Python str.split(): any unicode whitespace; for UTF-8 input
        // ASCII whitespace covers the practical cases in this corpus
        bool ws = *p == ' ' || *p == '\t' || *p == '\n' || *p == '\r' ||
                  *p == '\f' || *p == '\v';
        if (!ws && !in_word) { ++count; in_word = true; }
        if (ws) in_word = false;
    }
    return count;
}

// Recursive byte-budget splitter matching RecursiveTokenSplitter with the
// byte-count length function and the Vietnamese separator ladder.
// Chunks are written concatenated into `out` with their byte lengths in
// `lens_out`. Returns the chunk count, or -1 if either buffer is too small.
int vn_split_bytes(const char* text, int chunk_size, int chunk_overlap,
                   char* out, long out_cap, int* lens_out, int max_chunks);

namespace splitdetail {

const char* const SEPS[] = {"\n\n", "\n", ".", "!", "?", ";", " ", ""};
const int NSEPS = 8;

void split_on(const std::string& text, const std::string& sep,
              std::vector<std::string>& out) {
    out.clear();
    if (sep.empty()) {
        // one piece per UTF-8 codepoint (continuation bytes 10xxxxxx stay
        // attached), matching Python's per-character split
        size_t i = 0;
        while (i < text.size()) {
            size_t j = i + 1;
            while (j < text.size() && (text[j] & 0xC0) == 0x80) ++j;
            out.push_back(text.substr(i, j - i));
            i = j;
        }
        return;
    }
    // separator glued to the FOLLOWING piece
    size_t pos = 0, prev = 0;
    bool first = true;
    std::string pending;
    while ((pos = text.find(sep, prev)) != std::string::npos) {
        std::string piece = text.substr(prev, pos - prev);
        if (first) {
            if (!piece.empty()) out.push_back(piece);
            first = false;
        } else {
            std::string merged = pending + piece;
            if (!merged.empty()) out.push_back(merged);
        }
        pending = sep;
        prev = pos + sep.size();
    }
    std::string tail = text.substr(prev);
    if (first) {
        if (!tail.empty()) out.push_back(tail);
    } else {
        std::string merged = pending + tail;
        if (!merged.empty()) out.push_back(merged);
    }
}

std::string strip(const std::string& s) {
    size_t a = s.find_first_not_of(" \t\n\r\f\v");
    if (a == std::string::npos) return "";
    size_t b = s.find_last_not_of(" \t\n\r\f\v");
    return s.substr(a, b - a + 1);
}

void merge(const std::vector<std::string>& pieces, int chunk_size,
           int chunk_overlap, std::vector<std::string>& chunks) {
    std::vector<std::string> window;
    std::vector<long> lens;
    long total = 0;
    for (const auto& piece : pieces) {
        long plen = piece.size();
        if (total + plen > chunk_size && !window.empty()) {
            std::string joined;
            for (const auto& w : window) joined += w;
            joined = strip(joined);
            if (!joined.empty()) chunks.push_back(joined);
            while (!window.empty() &&
                   (total > chunk_overlap ||
                    (total + plen > chunk_size && total > 0))) {
                total -= lens.front();
                window.erase(window.begin());
                lens.erase(lens.begin());
            }
        }
        window.push_back(piece);
        lens.push_back(plen);
        total += plen;
    }
    std::string joined;
    for (const auto& w : window) joined += w;
    joined = strip(joined);
    if (!joined.empty()) chunks.push_back(joined);
}

void split_rec(const std::string& text, int chunk_size, int chunk_overlap,
               int sep_start, std::vector<std::string>& chunks) {
    int sep_idx = NSEPS - 1;
    int next_start = NSEPS;  // none
    for (int i = sep_start; i < NSEPS; ++i) {
        if (SEPS[i][0] == '\0') { sep_idx = i; break; }
        if (text.find(SEPS[i]) != std::string::npos) {
            sep_idx = i;
            next_start = i + 1;
            break;
        }
    }
    std::vector<std::string> pieces;
    split_on(text, SEPS[sep_idx], pieces);

    std::vector<std::string> small;
    for (auto& piece : pieces) {
        if ((int)piece.size() < chunk_size) {
            small.push_back(piece);
        } else {
            if (!small.empty()) {
                merge(small, chunk_size, chunk_overlap, chunks);
                small.clear();
            }
            if (next_start >= NSEPS) {
                chunks.push_back(piece);
            } else {
                split_rec(piece, chunk_size, chunk_overlap, next_start, chunks);
            }
        }
    }
    if (!small.empty()) merge(small, chunk_size, chunk_overlap, chunks);
}

}  // namespace splitdetail

int vn_split_bytes(const char* text, int chunk_size, int chunk_overlap,
                   char* out, long out_cap, int* lens_out, int max_chunks) {
    std::vector<std::string> chunks;
    std::string s(text);
    if (s.empty()) return 0;
    splitdetail::split_rec(s, chunk_size, chunk_overlap, 0, chunks);
    if ((int)chunks.size() > max_chunks) return -1;
    long need = 0;
    for (auto& c : chunks) need += (long)c.size();
    if (need > out_cap) return -1;
    char* w = out;
    for (size_t i = 0; i < chunks.size(); ++i) {
        std::memcpy(w, chunks[i].data(), chunks[i].size());
        w += chunks[i].size();
        lens_out[i] = (int)chunks[i].size();
    }
    return (int)chunks.size();
}

}  // extern "C"
